"""Overload-robustness plane: statement admission, fair queuing,
deadlines & KILL, and memstore write backpressure.

Reference analogs (SURVEY L10/L11): the tenant resource manager +
ObPxAdmission (statement-level concurrency quotas per tenant unit), the
large-query queue (ObThWorker lq_token yielding long statements to a
low-priority lane so point queries stop starving), query timeout /
QUERY KILL (ObSQLSessionInfo::check_session_status at operator
boundaries), and memstore writing throttling
(ob_tenant_freezer.cpp: writing_throttling_trigger_percentage ramping
writer sleeps until the freeze/flush catches up).

Shape here:

- ``AdmissionController``: every admitted statement checks out a
  per-tenant SLOT before binding.  Over-limit statements wait in a
  bounded per-tenant FIFO; slots freed are granted by weighted
  round-robin ACROSS tenants (a 4x-loud tenant cannot starve a quiet
  one).  A full queue — or a queue wait exceeding its budget — rejects
  fast with typed ``ServerBusy``, never a hang.
- **large-query lane**: a statement observed running past
  ``large_query_threshold_s`` yields its normal slot at the next
  checkpoint (the freed slot immediately admits a queued statement) and
  continues under the separate low-priority large-lane budget.
- ``StmtCtx`` + the thread-local ``checkpoint()``: the per-statement
  deadline (``query_timeout_s``, settable per session) and the KILL
  cancel flag are observed HOST-SIDE at result/span boundaries only
  (operator close in exec/plan.py, spill chunk, DTL slice join/merge,
  the session retry ladder) — no device-side branches, so obcheck and
  the static-shape compile keys stay clean.
- ``MemstoreThrottle``: per-tenant unflushed-memstore byte accounting
  at the TransService.write choke point; past
  ``writing_throttle_trigger_pct`` of ``memstore_limit_bytes`` writers
  pay a quadratically ramped sleep (and a freeze/flush of the fattest
  table is kicked), at the hard limit writes raise typed
  ``MemstoreFull`` until the flush catches up — bounded memory instead
  of OOM, reusing the PR-6 flush horizon.

Surfaces: gv$tenant_resource (server/virtual_tables.py), the
``admission.*`` metrics family, ``admission.wait`` trace spans, queued
time in gv$sql_audit, and QUEUED/RUNNING/KILLED in SHOW PROCESSLIST.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid
from contextlib import contextmanager
from typing import Callable, Optional

from oceanbase_tpu.server import metrics as qmetrics

qmetrics.declare("admission.admitted", "counter",
                 "statements granted a slot (labels: tenant, lane)")
qmetrics.declare("admission.queued", "counter",
                 "statements that had to wait in the admission FIFO")
qmetrics.declare("admission.rejected", "counter",
                 "statements rejected with ServerBusy (full queue or "
                 "queue-wait budget exceeded)")
qmetrics.declare("admission.timeouts", "counter",
                 "statements that died at their query_timeout_s "
                 "deadline (QueryTimeout)")
qmetrics.declare("admission.kills", "counter",
                 "statements cancelled via KILL (QueryKilled)")
qmetrics.declare("admission.demotions", "counter",
                 "statements that yielded their slot to the "
                 "large-query lane")
qmetrics.declare("admission.wait_s", "histogram",
                 "admission queue wait of admitted statements",
                 unit="s")
qmetrics.declare("admission.checkpoints", "counter",
                 "host-side cancel/deadline checkpoint observations")
qmetrics.declare("admission.px_downgrades", "counter",
                 "PX admission denials silently downgraded to serial "
                 "execution (labels: tenant)")
qmetrics.declare("admission.throttle_sleeps", "counter",
                 "writes that paid a memstore-pressure ramp sleep")
qmetrics.declare("admission.memstore_full", "counter",
                 "writes rejected at the memstore hard limit")


# ---------------------------------------------------------------------------
# typed overload errors (the degradation contract: never a hang)
# ---------------------------------------------------------------------------


class ServerBusy(RuntimeError):
    """Admission rejected the statement: the tenant's queue is full or
    the queue wait exceeded its budget.  Retry later / shed load."""


class QueryTimeout(TimeoutError):
    """The statement blew past its query_timeout_s deadline; observed
    host-side at a result-boundary checkpoint."""


class QueryKilled(RuntimeError):
    """The statement was cancelled via KILL [QUERY] <session_id> (or a
    propagated dtl.cancel on a remote fragment)."""


class MemstoreFull(RuntimeError):
    """A tenant's unflushed memstore bytes hit memstore_limit_bytes;
    writes fail typed until the freeze/flush catches up."""


# ---------------------------------------------------------------------------
# per-statement context + the thread-local checkpoint hook
# ---------------------------------------------------------------------------


class StmtCtx:
    """One admitted statement's cancel/deadline/lane state.

    The cancel flag and deadline are checked by ``checkpoint()`` at
    host-side result boundaries; ``ash_state`` (when provided) is the
    session's SHOW PROCESSLIST slot, flipped to ``killed`` by KILL so
    the state is visible while the victim unwinds."""

    __slots__ = ("session_id", "tenant", "sql", "deadline", "started",
                 "cancel", "kill_reason", "lane", "controller",
                 "ash_state", "token", "checkpoints", "queue_s",
                 "demoted", "demote_at", "slot")

    def __init__(self, session_id: int = 0, tenant: str = "sys",
                 sql: str = "", timeout_s: float | None = None,
                 controller: "AdmissionController | None" = None,
                 ash_state: dict | None = None):
        self.session_id = session_id
        self.tenant = tenant
        self.sql = sql
        self.started = time.monotonic()
        self.deadline = (self.started + float(timeout_s)
                         if timeout_s else None)
        self.cancel = threading.Event()
        self.kill_reason = ""
        self.lane = "normal"
        self.controller = controller
        self.ash_state = ash_state
        self.token = uuid.uuid4().hex[:16]  # dtl.cancel correlation
        self.checkpoints = 0
        self.queue_s = 0.0
        self.demoted = False
        # what this ctx actually HOLDS — None (nothing: rejected,
        # queued, or demotion-denied), "normal", "large", or
        # "disabled" (admission off at acquire time).  release() acts
        # on THIS, never on the live knobs: a rejected acquire must
        # not free someone else's slot, and toggling admission
        # mid-statement must not leak the one this ctx took.
        self.slot: str | None = None
        # the large-query threshold is read ONCE per statement: the
        # checkpoint hot path (every operator close) must not pay a
        # config-lock round trip
        self.demote_at = (
            self.started + controller.large_threshold_s()
            if controller is not None else None)

    def kill(self, reason: str = "killed"):
        self.kill_reason = reason or "killed"
        self.cancel.set()
        if self.ash_state is not None:
            self.ash_state["state"] = "killed"

    def check(self):
        """Raise QueryKilled / QueryTimeout when flagged; demote a
        long-running statement to the large-query lane.  Called from
        result-boundary checkpoints only (host side) — this is a HOT
        path (every operator close), so the happy case is one Event
        probe + one clock read; counters fold into one inc at
        release."""
        self.checkpoints += 1
        if self.cancel.is_set():
            qmetrics.inc("admission.kills", tenant=self.tenant)
            # the gv$tenant_resource lane counter too: a statement
            # killed while RUNNING was invisible there (only the
            # QUEUED path counted), so per-tenant kill accounting
            # undercounted exactly the expensive victims
            if self.controller is not None:
                with self.controller._lock:
                    self.controller._lane(self.tenant).kills += 1
            raise QueryKilled(
                f"statement killed ({self.kill_reason}): "
                f"session {self.session_id}")
        if self.deadline is None and self.demote_at is None:
            return
        now = time.monotonic()
        if self.deadline is not None and now > self.deadline:
            qmetrics.inc("admission.timeouts", tenant=self.tenant)
            if self.controller is not None:
                with self.controller._lock:
                    self.controller._lane(self.tenant).timeouts += 1
            raise QueryTimeout(
                f"query timeout after {now - self.started:.3f}s "
                f"(session {self.session_id})")
        if not self.demoted and self.demote_at is not None and \
                now > self.demote_at and self.controller is not None:
            self.controller.demote(self)

    def remaining_s(self) -> float | None:
        if self.deadline is None:
            return None
        return max(self.deadline - time.monotonic(), 0.0)


class RemoteCtx(StmtCtx):
    """A DTL fragment's cancel context on a data node: observes the
    coordinator-propagated cancel event, never demotes or re-enters the
    local admission queue."""

    def __init__(self, cancel_ev: threading.Event,
                 deadline_s: float | None = None, token: str = ""):
        super().__init__(session_id=-1, tenant="sys",
                         timeout_s=deadline_s)
        self.cancel = cancel_ev
        self.kill_reason = "dtl.cancel"
        self.token = token
        self.controller = None


_tls = threading.local()


def current() -> Optional[StmtCtx]:
    return getattr(_tls, "ctx", None)


@contextmanager
def activate(ctx: Optional[StmtCtx]):
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


def checkpoint():
    """The host-side cancel/deadline observation point.  A no-op off
    the statement path (no active ctx), so library code can call it
    unconditionally at its result boundaries."""
    ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        ctx.check()


# ---------------------------------------------------------------------------
# statement admission + weighted-round-robin fair queuing
# ---------------------------------------------------------------------------


class _Waiter:
    __slots__ = ("ctx", "event", "granted", "lane")

    def __init__(self, ctx: StmtCtx, lane: str = "normal"):
        self.ctx = ctx
        self.event = threading.Event()
        self.granted = False
        self.lane = lane


class _TenantLane:
    """Per-tenant admission state: active slot count + bounded FIFO."""

    __slots__ = ("name", "active", "large_active", "queue", "admitted",
                 "rejected", "queued", "kills", "timeouts")

    def __init__(self, name: str):
        self.name = name
        self.active = 0
        self.large_active = 0   # this tenant's share of the large lane
        self.queue: collections.deque[_Waiter] = collections.deque()
        self.admitted = 0
        self.rejected = 0
        self.queued = 0
        self.kills = 0
        self.timeouts = 0


class AdmissionController:
    """Process-wide statement admission (≙ the tenant worker quota +
    large query queue).  One instance per Database/NodeDatabase.

    Invariants:
    - total normal slots in use <= admission_slots;
    - per tenant, normal slots in use <= admission_tenant_slots;
    - per tenant, queued waiters <= admission_queue_limit (beyond it:
      typed ServerBusy immediately);
    - a freed slot is granted to the longest-waiting statement of the
      next tenant in weighted round-robin order — each tenant gets up
      to ``weight`` consecutive grants per rotation;
    - a queued statement never waits past min(queue budget, its own
      deadline): it fails typed, the queue slot frees.
    """

    def __init__(self, config, weight_of: Callable[[str], int]
                 | None = None):
        self.config = config
        self._weight_of = weight_of
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantLane] = {}
        self._rr: list[str] = []      # round-robin rotation order
        self._rr_pos = 0
        self._rr_credits = 0          # grants left for the rr head
        self._large_active = 0
        self._large_queue: collections.deque[_Waiter] = \
            collections.deque()
        #: session_id -> StmtCtx of the statement it is running NOW
        self._running: dict[int, StmtCtx] = {}
        #: sessions evicted by plain KILL <id>: every later statement
        #: on them fails typed (the client reconnects, MySQL-style);
        #: bounded — ancient ids age out once the set grows past cap
        self._killed_sessions: "collections.OrderedDict[int, bool]" = \
            collections.OrderedDict()
        self._KILLED_MAX = 4096
        self.demotions = 0

    # -- knobs (read live: ALTER SYSTEM SET retunes a running server) --
    def _slots(self) -> int:
        return int(self.config["admission_slots"])

    def _tenant_slots(self) -> int:
        return int(self.config["admission_tenant_slots"])

    def _queue_limit(self) -> int:
        return int(self.config["admission_queue_limit"])

    def _queue_timeout_s(self) -> float:
        return float(self.config["admission_queue_timeout_s"])

    def large_threshold_s(self) -> float:
        return float(self.config["large_query_threshold_s"])

    def _large_slots(self) -> int:
        return int(self.config["admission_large_slots"])

    def enabled(self) -> bool:
        return bool(self.config["enable_admission"]) and self._slots() > 0

    def _weight(self, tenant: str) -> int:
        if self._weight_of is None:
            return 1
        try:
            return max(int(self._weight_of(tenant)), 1)
        except Exception:  # noqa: BLE001 — a dropped tenant mid-read
            return 1

    def _lane(self, tenant: str) -> _TenantLane:
        lane = self._tenants.get(tenant)
        if lane is None:
            lane = self._tenants[tenant] = _TenantLane(tenant)
            self._rr.append(tenant)
        return lane

    # -- acquire / release ---------------------------------------------
    def acquire(self, ctx: StmtCtx):
        """Check a normal slot out for ``ctx``; blocks in the bounded
        per-tenant FIFO when over limit.  Raises ServerBusy (full queue
        or wait budget exceeded), QueryKilled (killed while queued) or
        QueryTimeout (statement deadline passed while queued).  Always
        returns or raises inside a bounded wait — never a hang."""
        # the ctx registers as this session's statement IMMEDIATELY —
        # KILL must reach a statement that is still QUEUED, not only
        # one that already holds a slot (the waiter loop below polls
        # the cancel flag); a failed acquire deregisters itself so a
        # dead ctx never lingers as the session's "running" statement
        with self._lock:
            self._running[ctx.session_id] = ctx
        try:
            self._acquire_inner(ctx)
        except BaseException:
            with self._lock:
                if self._running.get(ctx.session_id) is ctx:
                    del self._running[ctx.session_id]
            raise

    def _acquire_inner(self, ctx: StmtCtx):
        if not self.enabled():
            ctx.slot = "disabled"
            return
        t0 = time.monotonic()
        with self._lock:
            lane = self._lane(ctx.tenant)
            total = sum(x.active for x in self._tenants.values())
            if not lane.queue and total < self._slots() and \
                    lane.active < self._tenant_slots():
                lane.active += 1
                lane.admitted += 1
                ctx.slot = "normal"
                qmetrics.inc("admission.admitted", tenant=ctx.tenant,
                             lane="normal")
                return
            if len(lane.queue) >= max(self._queue_limit(), 0):
                lane.rejected += 1
                qmetrics.inc("admission.rejected", tenant=ctx.tenant)
                raise ServerBusy(
                    f"tenant {ctx.tenant}: admission queue full "
                    f"({len(lane.queue)} waiting, "
                    f"{lane.active} running)")
            w = _Waiter(ctx)
            lane.queue.append(w)
            lane.queued += 1
            qmetrics.inc("admission.queued", tenant=ctx.tenant)
        budget = self._queue_timeout_s()
        rem = ctx.remaining_s()
        if rem is not None:
            budget = min(budget, rem)
        deadline = t0 + budget
        while True:
            # poll in short slices so KILL lands while queued too
            if w.event.wait(timeout=min(
                    max(deadline - time.monotonic(), 0.0), 0.05)):
                break
            if ctx.cancel.is_set() or time.monotonic() >= deadline:
                with self._lock:
                    if w.granted:
                        break  # granted in the race window: keep it
                    try:
                        self._lane(ctx.tenant).queue.remove(w)
                    except ValueError:
                        pass
                if ctx.cancel.is_set():
                    with self._lock:
                        lane.kills += 1
                    qmetrics.inc("admission.kills", tenant=ctx.tenant)
                    raise QueryKilled(
                        f"statement killed while queued "
                        f"(session {ctx.session_id})")
                rem = ctx.remaining_s()
                if rem is not None and rem <= 0:
                    with self._lock:
                        lane.timeouts += 1
                    qmetrics.inc("admission.timeouts",
                                 tenant=ctx.tenant)
                    raise QueryTimeout(
                        f"query timeout while queued "
                        f"(session {ctx.session_id})")
                with self._lock:
                    lane.rejected += 1
                qmetrics.inc("admission.rejected", tenant=ctx.tenant)
                raise ServerBusy(
                    f"tenant {ctx.tenant}: admission queue wait "
                    f"exceeded {budget:.3f}s")
        ctx.slot = "normal"  # _grant_locked counted us into lane.active
        ctx.queue_s = time.monotonic() - t0
        qmetrics.observe("admission.wait_s", ctx.queue_s,
                         tenant=ctx.tenant)
        qmetrics.inc("admission.admitted", tenant=ctx.tenant,
                     lane="normal")

    def release(self, ctx: StmtCtx):
        """Return whatever ``ctx`` actually holds (ctx.slot — set at
        grant time, NOT re-derived from the live knobs: a rejected
        acquire holds nothing, and an admission toggle mid-statement
        must neither leak nor double-free a slot)."""
        if ctx.checkpoints:
            # folded at the statement boundary: one inc, not one per
            # operator close (the metrics_bench <=2% contract)
            qmetrics.inc("admission.checkpoints", ctx.checkpoints)
        with self._lock:
            cur = self._running.get(ctx.session_id)
            if cur is ctx:
                del self._running[ctx.session_id]
            slot, ctx.slot = ctx.slot, None
            if slot == "large":
                if self._large_active > 0:
                    self._large_active -= 1
                lane = self._tenants.get(ctx.tenant)
                if lane is not None and lane.large_active > 0:
                    lane.large_active -= 1
                self._grant_large_locked()
            elif slot == "normal":
                lane = self._tenants.get(ctx.tenant)
                if lane is not None and lane.active > 0:
                    lane.active -= 1
                self._grant_locked()
            # slot None ("rejected"/"demotion-denied") or "disabled":
            # nothing was held — nothing to free

    def demote(self, ctx: StmtCtx):
        """Yield ``ctx``'s normal slot to the queue and move it to the
        low-priority large-query lane (point queries stop starving
        behind a scan).  When the large lane itself is saturated the
        statement waits — bounded by its own deadline/cancel flags —
        before continuing."""
        with self._lock:
            ctx.demoted = True
            if ctx.slot != "normal":
                return  # nothing to yield (disabled / already large)
            lane = self._tenants.get(ctx.tenant)
            if lane is not None and lane.active > 0:
                lane.active -= 1
            ctx.slot = None  # held by the queue now, not by us
            self._grant_locked()  # the freed slot admits a waiter NOW
            self.demotions += 1
            qmetrics.inc("admission.demotions", tenant=ctx.tenant)
            if self._large_active < self._large_slots():
                self._large_active += 1
                self._lane(ctx.tenant).large_active += 1
                ctx.lane = "large"
                ctx.slot = "large"
                qmetrics.inc("admission.admitted", tenant=ctx.tenant,
                             lane="large")
                return
            w = _Waiter(ctx, lane="large")
            self._large_queue.append(w)
        while not w.event.wait(timeout=0.05):
            if ctx.cancel.is_set() or (
                    ctx.deadline is not None
                    and time.monotonic() > ctx.deadline):
                with self._lock:
                    if w.granted:
                        break
                    try:
                        self._large_queue.remove(w)
                    except ValueError:
                        pass
                # holding NOTHING now (the normal slot was yielded,
                # the large lane denied); re-raise through the
                # ordinary checkpoint machinery (kills/timeouts
                # counted once, there)
                ctx.lane = "large_denied"
                ctx.check()
                return
        ctx.lane = "large"
        ctx.slot = "large"
        qmetrics.inc("admission.admitted", tenant=ctx.tenant,
                     lane="large")

    # -- grant machinery (callers hold self._lock) ---------------------
    def _grant_locked(self):
        """Hand freed capacity to waiters in weighted round-robin order
        across tenants."""
        while True:
            total = sum(x.active for x in self._tenants.values())
            if total >= self._slots():
                return
            w = self._next_waiter_locked()
            if w is None:
                return
            lane = self._lane(w.ctx.tenant)
            lane.active += 1
            lane.admitted += 1
            w.granted = True
            w.event.set()

    def _next_waiter_locked(self) -> _Waiter | None:
        """The WRR pick: rotate tenant order, spending up to ``weight``
        credits per tenant before moving on; tenants over their own cap
        or with empty queues are skipped."""
        if not self._rr:
            return None
        n = len(self._rr)
        scanned = 0
        while scanned <= n:
            if self._rr_pos >= len(self._rr):
                self._rr_pos = 0
            name = self._rr[self._rr_pos]
            lane = self._tenants[name]
            if self._rr_credits <= 0:
                self._rr_credits = self._weight(name)
            if lane.queue and lane.active < self._tenant_slots():
                self._rr_credits -= 1
                if self._rr_credits <= 0:
                    self._rr_pos = (self._rr_pos + 1) % len(self._rr)
                return lane.queue.popleft()
            # nothing grantable here: move on, dropping stale credits
            self._rr_credits = 0
            self._rr_pos = (self._rr_pos + 1) % len(self._rr)
            scanned += 1
        return None

    def _grant_large_locked(self):
        while self._large_queue and \
                self._large_active < self._large_slots():
            w = self._large_queue.popleft()
            self._large_active += 1
            self._lane(w.ctx.tenant).large_active += 1
            w.granted = True
            w.event.set()

    # -- KILL ----------------------------------------------------------
    def kill(self, session_id: int, query_only: bool = True) -> bool:
        """KILL QUERY <id>: flag the session's running (or queued)
        statement; the victim unwinds at its next checkpoint with
        typed QueryKilled.  Plain KILL <id> additionally EVICTS the
        session — every later statement on it fails typed, like the
        MySQL connection kill (the client reconnects).  -> True when a
        statement was cancelled or the session was evicted."""
        with self._lock:
            ctx = self._running.get(session_id)
            evicted = False
            if not query_only:
                while len(self._killed_sessions) >= self._KILLED_MAX:
                    self._killed_sessions.popitem(last=False)
                self._killed_sessions[session_id] = True
                evicted = True
        if ctx is not None:
            ctx.kill(reason="KILL QUERY" if query_only else "KILL")
        return ctx is not None or evicted

    def check_session(self, session_id: int):
        """Statement-entry gate: a session evicted by plain KILL takes
        no more statements (raises typed QueryKilled)."""
        with self._lock:
            killed = session_id in self._killed_sessions
        if killed:
            raise QueryKilled(
                f"session {session_id} was killed; reconnect")

    def forget_session(self, session_id: int):
        """Session teardown: drop the eviction flag (ids are unique per
        Database, but don't let a dead flag outlive its session)."""
        with self._lock:
            self._killed_sessions.pop(session_id, None)
            self._running.pop(session_id, None)

    # -- observability -------------------------------------------------
    def stats(self) -> list[dict]:
        """gv$tenant_resource rows (per tenant)."""
        with self._lock:
            out = []
            for name in sorted(self._tenants):
                lane = self._tenants[name]
                out.append({
                    "tenant": name,
                    "slots_in_use": lane.active,
                    "slots_total": self._tenant_slots(),
                    "queue_depth": len(lane.queue),
                    "queue_limit": self._queue_limit(),
                    "weight": self._weight(name),
                    "admitted": lane.admitted,
                    "queued": lane.queued,
                    "rejected": lane.rejected,
                    "kills": lane.kills,
                    "timeouts": lane.timeouts,
                    # THIS tenant's demoted statements; large_slots is
                    # the shared process-wide lane capacity
                    "large_in_use": lane.large_active,
                    "large_slots": self._large_slots(),
                })
            return out

    def queue_depth(self, tenant: str) -> int:
        with self._lock:
            lane = self._tenants.get(tenant)
            return len(lane.queue) if lane is not None else 0

    def active_slots(self) -> int:
        with self._lock:
            return sum(x.active for x in self._tenants.values()) + \
                self._large_active


# ---------------------------------------------------------------------------
# memstore write backpressure
# ---------------------------------------------------------------------------


class MemstoreThrottle:
    """Per-tenant unflushed-memstore byte accounting + writer throttle
    (≙ writing throttling: the freezer's trigger percentage ramping
    writer sleeps, the hard limit bouncing writes).

    ``note_write`` is called at the TransService.write choke point (all
    writers: session DML, PDML workers, OBKV); ``admit_write`` gates
    BEFORE the memtable append.  ``on_flush`` (wired to the engine's
    flush listener) re-bases a table's accounting from the rows still
    resident after a freeze/flush."""

    def __init__(self, config, flush_cb: Callable[[str], None]
                 | None = None):
        self.config = config
        self.flush_cb = flush_cb
        self._lock = threading.Lock()
        #: table -> {"bytes": int, "rows": int}
        self._tables: dict[str, dict] = {}
        # running total of unflushed bytes, adjusted at every mutation
        # (write/flush/drop): admit_write sits on EVERY row write's hot
        # path, so it must not pay an O(n_tables) sum under the lock
        self._used_bytes = 0
        self._flush_inflight = False
        self.throttle_sleeps = 0
        self.full_rejections = 0
        self.peak_bytes = 0

    @staticmethod
    def row_bytes(values: dict) -> int:
        n = 64  # key/version-chain overhead estimate
        for v in values.values():
            if isinstance(v, str):
                n += 16 + len(v)
            elif isinstance(v, (list, tuple)):
                n += 16 + 8 * len(v)
            else:
                n += 8
        return n

    def enabled(self) -> bool:
        return bool(self.config["enable_rate_limit"])

    def limit_bytes(self) -> int:
        return int(self.config["memstore_limit_bytes"])

    def trigger_bytes(self) -> int:
        pct = int(self.config["writing_throttle_trigger_pct"])
        return self.limit_bytes() * pct // 100

    def used_bytes(self) -> int:
        with self._lock:
            return self._used_bytes

    def admit_write(self, table: str, values: dict):
        """Gate + account one row write.  Raises MemstoreFull at the
        hard limit; pays a ramped sleep past the trigger (and kicks a
        freeze/flush of the fattest table so pressure clears)."""
        if not self.enabled():
            return
        nbytes = self.row_bytes(values)
        limit = self.limit_bytes()
        trigger = self.trigger_bytes()
        with self._lock:
            used = self._used_bytes
            # ONE accept/reject decision, made under the lock: a
            # rejected row is NEVER accounted (it never reaches the
            # memtable), and an accepted one must not be re-judged
            # against its own bytes after the fact
            rejected = used + nbytes > limit
            if rejected:
                self.full_rejections += 1
                qmetrics.inc("admission.memstore_full")
            else:
                ent = self._tables.setdefault(
                    table, {"bytes": 0, "rows": 0})
                ent["bytes"] += nbytes
                ent["rows"] += 1
                used += nbytes
                self._used_bytes = used
                self.peak_bytes = max(self.peak_bytes, used)
            fattest = self._fattest_locked()
            # take the one-shot flush token ONLY when it will actually
            # be spent — a kick with no flushable table (first-ever
            # write over the limit) or no callback must not wedge the
            # token and disable pressure flushes forever
            kick = (rejected or used > trigger) and \
                fattest is not None and self.flush_cb is not None and \
                self._take_flush_locked()
        if kick:
            try:
                self.flush_cb(fattest)
            finally:
                with self._lock:
                    self._flush_inflight = False
        if rejected:
            raise MemstoreFull(
                f"memstore limit reached ({used}/{limit} bytes "
                f"unflushed); retry after the flush catches up")
        if used > trigger and limit > trigger:
            # quadratic ramp: barely over the trigger sleeps ~0, near
            # the hard limit sleeps the full budget (≙ the reference's
            # decaying write throughput as memstore fills)
            frac = (used - trigger) / float(limit - trigger)
            delay = min(frac * frac, 1.0) * float(
                self.config["writing_throttle_max_sleep_s"])
            if delay > 0.0005:
                self.throttle_sleeps += 1
                qmetrics.inc("admission.throttle_sleeps")
                time.sleep(delay)

    def _fattest_locked(self) -> str | None:
        if not self._tables:
            return None
        return max(self._tables, key=lambda t: self._tables[t]["bytes"])

    def _take_flush_locked(self) -> bool:
        if self._flush_inflight:
            return False
        self._flush_inflight = True
        return True

    def on_flush(self, table: str, remaining_rows: int):
        """Engine flush listener: re-base ``table``'s accounting from
        the rows still resident (the flush horizon can hold back
        versions a live transaction's conflict check needs)."""
        with self._lock:
            ent = self._tables.get(table)
            if ent is None:
                return
            rows = max(ent["rows"], 1)
            avg = ent["bytes"] / rows
            # a flush only SHRINKS residency: clamp the re-base so avg
            # drift (or memtable rows this accounting never saw, e.g.
            # replayed writes) cannot push the estimate UP past what
            # was admitted — the hard limit must stay a hard limit
            ent["rows"] = max(int(remaining_rows), 0)
            shrunk = min(int(ent["rows"] * avg), ent["bytes"])
            self._used_bytes -= ent["bytes"] - shrunk
            ent["bytes"] = shrunk

    def drop_table(self, table: str):
        with self._lock:
            ent = self._tables.pop(table, None)
            if ent is not None:
                self._used_bytes -= ent["bytes"]

    def reset_peak(self):
        """Start a fresh peak-bytes window (benches measure a phase,
        not the process lifetime)."""
        with self._lock:
            self.peak_bytes = self._used_bytes

    def state(self) -> str:
        if not self.enabled():
            return "off"
        used = self.used_bytes()
        if used >= self.limit_bytes():
            return "full"
        if used > self.trigger_bytes():
            return "throttle"
        return "ok"

    def stats(self) -> dict:
        return {
            "memstore_bytes": self.used_bytes(),
            "memstore_limit_bytes": self.limit_bytes(),
            "throttle_trigger_bytes": self.trigger_bytes(),
            "throttle_state": self.state(),
            "throttle_sleeps": self.throttle_sleeps,
            "memstore_full_rejections": self.full_rejections,
            "memstore_peak_bytes": self.peak_bytes,
        }
