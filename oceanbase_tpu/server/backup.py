"""Backup service: full + incremental physical backup, WAL archiving,
point-in-time restore.

Reference analog: data backup/restore (src/storage/backup,
src/rootserver/backup) + the log archive service
(src/logservice/archiveservice) feeding PITR
(src/storage/restore).  Model:

- FULL backup     = checkpoint + copy of the data tree + manifest
- INCREMENTAL     = copy of files NEW since the base backup's manifest
  (segment files are immutable once written, so name+size identity is
  sound; manifests/slog/config/WAL always re-copy — they're tiny or
  append-only)
- WAL archive     = copy of the append-only replica logs; re-archiving
  appends only the suffix (≙ archive progress per log stream)
- PITR            = restore chain -> rewrite the WAL keeping commit
  records with version <= the target timestamp (uncommitted/later txs
  never replay) -> boot

Restore = `Database(restored_root)` — recovery IS the restore path.
"""

from __future__ import annotations

import json
import os
import shutil
import time

from oceanbase_tpu.server import admission as qadmission
from oceanbase_tpu.server.diskmgr import (
    DiskFull,
    DiskIOError,
    wrap_disk_error,
)
from oceanbase_tpu.storage.integrity import CorruptionError

MANIFEST = "BACKUP_MANIFEST.json"


def _faults(db):
    """The node's fault plane (net/faults.FaultPlane) when armed —
    backup writes consult it per destination file (kind="backup")."""
    return getattr(db, "faults", None)


def _check_backup_write(faults, dst: str):
    if faults is not None:
        faults.check_write("backup", dst)


def _write_json_atomic(path: str, obj):
    """Manifest/state writes publish by rename: a failed write leaves
    the previous generation intact, never a torn current file."""
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def _walk(root: str) -> dict[str, int]:
    out = {}
    for dirpath, _dirs, files in os.walk(root):
        for f in files:
            p = os.path.join(dirpath, f)
            out[os.path.relpath(p, root)] = os.path.getsize(p)
    return out


def verify_wal_file(path: str):
    """Verify every entry crc64 of one replica WAL copy; raises
    CorruptionError on the first mismatch.  A torn TAIL (header/payload
    running past EOF) is a crash artifact the boot scan truncates, not
    corruption — but a bad crc on complete bytes means the archive
    would preserve rot forever, so the backup must fail loudly."""
    from oceanbase_tpu.palf.log import _MAGIC, scan_wal

    with open(path, "rb") as fh:
        buf = fh.read()
    if not buf.startswith(_MAGIC):
        if buf:
            raise CorruptionError(f"backup WAL bad magic: {path}",
                                  kind="wal", path=path)
        return
    _entries, _valid_off, crc_failed_lsn = scan_wal(buf)
    if crc_failed_lsn:
        raise CorruptionError(
            f"backup WAL entry lsn={crc_failed_lsn} crc mismatch: "
            f"{path}", kind="wal", path=path)


def _verify_backup_wal(dest: str):
    """Backup-time gate: never archive corrupt WAL bytes — verify every
    replica log in the copied tree, removing the half-made backup on
    failure so a retry cannot resume from poison."""
    try:
        for dirpath, _dirs, files in os.walk(dest):
            for f in files:
                if f.startswith("replica_") and f.endswith(".log"):
                    verify_wal_file(os.path.join(dirpath, f))
    except CorruptionError:
        shutil.rmtree(dest, ignore_errors=True)
        raise


def full_backup(db, dest: str) -> str:
    """Checkpoint + full copy; returns the backup dir."""
    if db.root is None:
        raise ValueError("in-memory database cannot be backed up")
    db.checkpoint()
    faults = _faults(db)
    os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)

    def _copy(src, dst, *, follow_symlinks=True):
        # wrap to typed IMMEDIATELY: copytree folds bare OSErrors into
        # a shutil.Error that loses the errno (ENOSPC vs EIO)
        try:
            _check_backup_write(faults, dst)
            return shutil.copy2(src, dst,
                                follow_symlinks=follow_symlinks)
        except OSError as exc:
            raise wrap_disk_error(exc, f"backup copy {dst}") from exc

    try:
        shutil.copytree(db.root, dest, dirs_exist_ok=False,
                        copy_function=_copy)
        _verify_backup_wal(dest)
        files = _walk(dest)
        files.pop(MANIFEST, None)
        _check_backup_write(faults, os.path.join(dest, MANIFEST))
        _write_json_atomic(os.path.join(dest, MANIFEST),
                           {"kind": "full", "base": None,
                            "ts": time.time(), "files": files})
    except (OSError, DiskFull, DiskIOError) as exc:
        # a half-made backup must not survive to be resumed/restored
        shutil.rmtree(dest, ignore_errors=True)
        raise wrap_disk_error(exc, f"full backup to {dest}") from exc
    return dest


def incremental_backup(db, dest: str, base: str) -> str:
    """Copy only files new/changed since the ``base`` backup.

    Segment files are write-once (compaction writes NEW ids), so a file
    present in the base with the same size is skipped; everything else
    (manifest.json, slog, config, WAL logs, meta) re-copies."""
    if db.root is None:
        raise ValueError("in-memory database cannot be backed up")
    with open(os.path.join(base, MANIFEST)) as fh:
        base_m = json.load(fh)
    db.checkpoint()
    faults = _faults(db)
    os.makedirs(dest, exist_ok=False)
    copied, skipped = {}, 0
    try:
        for rel, size in _walk(db.root).items():
            qadmission.checkpoint()  # KILL/deadline between file copies
            if rel == MANIFEST:
                continue
            src = os.path.join(db.root, rel)
            immutable = "segments" + os.sep in rel or rel.endswith(".seg")
            if immutable and base_m["files"].get(rel) == size:
                skipped += 1
                continue
            dst = os.path.join(dest, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            _check_backup_write(faults, dst)
            shutil.copy2(src, dst)
            copied[rel] = size
        _verify_backup_wal(dest)
        _check_backup_write(faults, os.path.join(dest, MANIFEST))
        _write_json_atomic(os.path.join(dest, MANIFEST),
                           {"kind": "incremental",
                            "base": os.path.abspath(base),
                            "ts": time.time(), "files": copied,
                            "skipped": skipped})
    except OSError as exc:
        # a half-made increment must not survive as a chain link
        shutil.rmtree(dest, ignore_errors=True)
        raise wrap_disk_error(
            exc, f"incremental backup to {dest}") from exc
    return dest


def archive_wal(db, dest: str):
    """Append-only WAL archiving: copies each replica log's NEW suffix
    (byte offset recorded per file — ≙ archive progress points)."""
    os.makedirs(dest, exist_ok=True)
    faults = _faults(db)
    state_p = os.path.join(dest, "ARCHIVE_STATE.json")
    state = {}
    if os.path.exists(state_p):
        with open(state_p) as fh:
            state = json.load(fh)
    for dirpath, _dirs, files in os.walk(db.root):
        qadmission.checkpoint()  # KILL/deadline between directories
        for f in files:
            if not f.endswith(".log"):
                continue
            src = os.path.join(dirpath, f)
            rel = os.path.relpath(src, db.root)
            dst = os.path.join(dest, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            start = state.get(rel, 0)
            size = os.path.getsize(src)
            if size > start:
                try:
                    _check_backup_write(faults, dst)
                    with open(src, "rb") as s, open(dst, "ab") as d:
                        s.seek(start)
                        shutil.copyfileobj(s, d)
                        d.flush()
                        os.fsync(d.fileno())
                except OSError as exc:
                    # append-only discipline: truncate the archive copy
                    # back to the recorded progress point so the next
                    # round re-appends from a clean suffix boundary
                    try:
                        with open(dst, "ab") as d:
                            d.truncate(start)
                    except OSError:
                        pass
                    raise wrap_disk_error(
                        exc, f"wal archive {dst}") from exc
                state[rel] = size
    try:
        _check_backup_write(faults, state_p)
        _write_json_atomic(state_p, state)
    except OSError as exc:
        raise wrap_disk_error(exc, "wal archive state") from exc
    return dest


def restore_chain(backup: str, target: str) -> str:
    """Materialize a backup (full or incremental chain) at ``target``."""
    chain = []
    cur = backup
    while cur is not None:
        with open(os.path.join(cur, MANIFEST)) as fh:
            m = json.load(fh)
        chain.append(cur)
        cur = m["base"]
    base = chain[-1]
    shutil.copytree(base, target, dirs_exist_ok=False)
    for inc in reversed(chain[:-1]):
        qadmission.checkpoint()  # KILL/deadline between increments
        for dirpath, _dirs, files in os.walk(inc):
            for f in files:
                if f == MANIFEST:
                    continue
                src = os.path.join(dirpath, f)
                rel = os.path.relpath(src, inc)
                dst = os.path.join(target, rel)
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                shutil.copy2(src, dst)
    os.remove(os.path.join(target, MANIFEST))
    return target


def overlay_archive(archive: str, target: str):
    """Lay archived WAL over a restored tree (archived logs are always
    at least as long as the backup's copies)."""
    for dirpath, _dirs, files in os.walk(archive):
        qadmission.checkpoint()  # KILL/deadline between directories
        for f in files:
            if f == "ARCHIVE_STATE.json":
                continue
            src = os.path.join(dirpath, f)
            rel = os.path.relpath(src, archive)
            dst = os.path.join(target, rel)
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            shutil.copy2(src, dst)


def pitr_cut(target: str, until_version: int):
    """Rewrite every WAL file under ``target`` dropping COMMIT records
    with version > until_version: transactions past the cut never
    replay, giving a consistent snapshot at the target point
    (≙ restoring to a timestamp, src/storage/restore).

    Every entry's stored crc64 is VERIFIED before the rewrite: the cut
    re-encodes entries, which would otherwise launder corrupt payloads
    into fresh valid checksums the restored node then trusts."""
    from oceanbase_tpu.palf.log import _BASE_PAYLOAD, _MAGIC, LogEntry, \
        scan_wal

    for dirpath, _dirs, files in os.walk(target):
        for f in files:
            if not (f.startswith("replica_") and f.endswith(".log")):
                continue
            path = os.path.join(dirpath, f)
            with open(path, "rb") as fh:
                buf = fh.read()
            if not buf.startswith(_MAGIC):
                continue
            entries, _valid_off, crc_failed_lsn = scan_wal(buf)
            if crc_failed_lsn:
                # a torn tail the boot scan would truncate is fine;
                # a complete entry failing its crc is rot
                raise CorruptionError(
                    f"PITR source WAL entry lsn={crc_failed_lsn} crc "
                    f"mismatch: {path}", kind="wal", path=path)
            # a recycled WAL leads with its base record — preserve it
            # verbatim and renumber the tail from base_lsn + 1 (recycled
            # entries are checkpointed history at/below the cut)
            base_rec = None
            if entries and entries[0].payload == _BASE_PAYLOAD:
                base_rec = entries[0]
                entries = entries[1:]
            kept: list[LogEntry] = []
            for e in entries:
                try:
                    rec = json.loads(e.payload.decode())
                except Exception:
                    rec = {}
                if rec.get("op") == "commit" and \
                        rec.get("version", 0) > until_version:
                    continue  # drop: this tx commits after the cut
                kept.append(e)
            # re-number LSNs densely (accept() requires a gapless log)
            first = (base_rec.lsn + 1) if base_rec is not None else 1
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(_MAGIC)
                if base_rec is not None:
                    fh.write(base_rec.encode())
                for i, e in enumerate(kept, first):
                    fh.write(LogEntry(e.term, i, e.payload).encode())
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
