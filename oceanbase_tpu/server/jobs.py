"""DBMS job scheduler: periodic maintenance jobs per database.

Reference analog: the dbms_job/dbms_scheduler services
(src/observer/dbms_job, dbms_scheduler) running stats auto-gather and
maintenance windows (daily major freeze).  Jobs run on one daemon
thread; every run is recorded for v$dbms_jobs.

Built-ins:
- stats_gather   — ANALYZE tables whose row count drifted >= 50% since
  the last gather (≙ DBMS_STATS auto gather)
- auto_compact   — major-compact tables whose L0/L1 segment count
  exceeds the minor trigger (≙ the daily merge window)

Custom SQL jobs register via ``schedule(name, interval_s, sql)``.
"""

from __future__ import annotations

import threading
import time


class JobScheduler:
    def __init__(self, db, tick_s: float = 1.0):
        self.db = db
        self.tick_s = tick_s
        self.jobs: dict[str, dict] = {}
        self.history: list[dict] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._stats_seen: dict[str, int] = {}

    # ------------------------------------------------------------------
    def register_builtins(self, stats_interval_s: float = 600.0,
                          compact_interval_s: float = 3600.0):
        self.schedule_fn("stats_gather", stats_interval_s,
                         self._stats_gather)
        self.schedule_fn("auto_compact", compact_interval_s,
                         self._auto_compact)

    def schedule_fn(self, name: str, interval_s: float, fn):
        self.jobs[name] = {"interval": interval_s, "fn": fn,
                           "next": time.monotonic() + interval_s,
                           "runs": 0, "failures": 0, "last_s": 0.0}

    def schedule(self, name: str, interval_s: float, sql: str):
        """A recurring SQL job (≙ DBMS_SCHEDULER.create_job)."""

        def run():
            s = self.db.session()
            try:
                s.execute(sql)
            finally:
                s.close()

        self.schedule_fn(name, interval_s, run)

    def cancel(self, name: str):
        self.jobs.pop(name, None)

    # ------------------------------------------------------------------
    def _stats_gather(self):
        t = self.db.tenants.get("sys")
        if t is None:
            return
        s = self.db.session()
        try:
            for name in list(t.engine.tables):
                if name.startswith("__idx__"):
                    continue
                ts = t.engine.tables[name]
                rows = ts.tablet.row_count_estimate()
                seen = self._stats_seen.get(name)
                if seen is None or (rows and abs(rows - seen) * 2 >=
                                    max(seen, 1)):
                    s.execute(f"analyze table {name}")
                    self._stats_seen[name] = rows
        finally:
            s.close()

    def _auto_compact(self):
        t = self.db.tenants.get("sys")
        if t is None:
            return
        trigger = int(self.db.config["minor_compact_trigger"])
        for name in list(t.engine.tables):
            ts = t.engine.tables[name]
            # the trigger is an UNCOMPACTED (below-baseline) segment
            # count per partition — total segments would re-compact an
            # already-major-compacted partitioned table forever
            per_part: dict = {}
            for seg, part in ts.tablet.segment_locations():
                if seg.level < 2:
                    per_part[part] = per_part.get(part, 0) + 1
            if per_part and max(per_part.values()) > trigger:
                t.engine.major_compact(name)

    # ------------------------------------------------------------------
    def _loop(self):
        while not self._stop.wait(self.tick_s):
            now = time.monotonic()
            for name, j in list(self.jobs.items()):
                if now < j["next"]:
                    continue
                ts = time.time()       # record timestamp (wall)
                t0 = time.monotonic()  # elapsed source (step-proof)
                ok, err = True, ""
                try:
                    j["fn"]()
                except Exception as e:  # noqa: BLE001 — record + continue
                    ok, err = False, f"{type(e).__name__}: {e}"
                    j["failures"] += 1
                j["runs"] += 1
                j["last_s"] = time.monotonic() - t0
                j["next"] = time.monotonic() + j["interval"]
                self.history.append({
                    "ts": ts, "job": name, "ok": ok, "error": err,
                    "elapsed_s": j["last_s"]})
                del self.history[:-1000]

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop,
                                            daemon=True,
                                            name="dbms-jobs")
            self._thread.start()
        return self

    def stop(self):
        """Stop and WAIT for any in-flight job: Database.close() must not
        tear tenants down under a running ANALYZE/compaction."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
            self._thread = None
