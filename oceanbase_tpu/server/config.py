"""Declarative configuration registry.

Reference analog: the parameter seed file with DEF_INT/DEF_BOOL/DEF_CAP
macros (src/share/parameter/ob_parameter_seed.ipp — 738 definitions) with
checkers (src/share/config/ob_config_helper.h), runtime-settable via
ALTER SYSTEM SET, persisted, with per-tenant overlays
(src/observer/omt/ob_tenant_config_mgr.h).

Same pattern here: one registry of typed, validated, documented parameters;
hot-reloadable; persisted to the data directory; per-tenant overlay maps.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional


@dataclass
class ParamDef:
    name: str
    default: Any
    ptype: str             # int | bool | str | float | cap
    doc: str
    validator: Optional[Callable[[Any], bool]] = None
    reboot_required: bool = False


_DEFS: dict[str, ParamDef] = {}


def DEF(name, default, ptype, doc, validator=None, reboot=False):
    _DEFS[name] = ParamDef(name, default, ptype, doc, validator, reboot)
    return name


def _pos(v):
    return v > 0


def _nonneg(v):
    return v >= 0


def _frac(v):
    return 0.0 <= v <= 1.0


# ---------------------------------------------------------------------------
# parameter seed (≙ ob_parameter_seed.ipp) — the engine's knobs
# ---------------------------------------------------------------------------

# SQL engine
DEF("max_batch_size", 65536, "int",
    "row batch capacity per morsel on device (multiple of 8*128 lanes)",
    _pos)
DEF("default_group_capacity", 1 << 16, "int",
    "default static capacity for GROUP BY outputs", _pos)
DEF("join_capacity_factor", 1.5, "float",
    "safety multiplier over join cardinality estimates", _pos)
DEF("max_capacity_retry", 3, "int",
    "re-plan attempts (4x budget each) after CapacityOverflow", _nonneg)
DEF("sql_work_area_rows", 1 << 22, "int",
    "per-query work-area row budget; inputs estimated above it stream "
    "through the disk spill tier (≙ ObTenantSqlMemoryManager work areas)",
    _pos)
DEF("enable_sql_spill", True, "bool",
    "route over-budget sorts/joins/group-bys through the temp-file "
    "spill tier instead of failing on CapacityOverflow")
DEF("enable_sql_plan_monitor", True, "bool",
    "collect per-operator row counts/timings (≙ sql_plan_monitor); an "
    "explicit EXPLAIN ANALYZE forces collection for its own statement "
    "regardless")
DEF("plan_monitor_sample_every", 16, "int",
    "per-plan ledger sampling: the first executions of a logical plan "
    "always collect per-operator rows, then every Nth (1 = collect "
    "every execution); unsampled executions run the same monitored "
    "executable but skip the host transfer and ledger record — "
    "hot-reloadable via ALTER SYSTEM SET", _pos)
DEF("enable_plan_feedback", True, "bool",
    "cardinality feedback (gv$plan_feedback): monitored executions "
    "record observed per-operator rows per logical plan hash; binds "
    "consult the store to correct out_capacity, and CapacityOverflow "
    "retries jump straight to the reported budget instead of riding "
    "the blind 4x ladder — hot-reloadable via ALTER SYSTEM SET")
DEF("plan_regress_threshold", 2.0, "float",
    "plan-regression watchdog: a plan whose latency EWMA exceeds its "
    "frozen warmup baseline by this factor is flagged regressed in "
    "gv$plan_history — hot-reloadable via ALTER SYSTEM SET (each "
    "execution re-reads it)", lambda v: v >= 1.0)
DEF("plan_feedback_entries", 2048, "int",
    "bounded gv$plan_feedback store: logical plan hashes kept (LRU); "
    "takes effect for new Database instances (ring size is bound at "
    "boot)", _pos)
DEF("plan_history_entries", 1024, "int",
    "bounded gv$plan_history store: logical plan hashes kept (LRU); "
    "takes effect for new Database instances (ring size is bound at "
    "boot)", _pos)
DEF("enable_plan_cache", True, "bool",
    "cache bound physical plans keyed by parameterized SQL text")
DEF("plan_cache_mem_limit", 512 << 20, "cap",
    "plan cache memory budget in bytes", _pos)
DEF("enable_shape_buckets", True, "bool",
    "pad device relations materialized from storage to geometric "
    "capacity buckets (dead lanes masked) so a table growing inside "
    "one bucket reuses the same compiled XLA executable instead of "
    "retracing every plan per row-count change")
DEF("shape_bucket_growth", 2.0, "float",
    "geometric growth factor of the storage-materialization bucket "
    "ladder (derived chunk/exchange budgets use the default ladder)",
    lambda v: v >= 1.125)
DEF("shape_bucket_floor", 64, "int",
    "smallest capacity bucket (tables below it pad up to the floor); "
    "governs storage materialization — derived chunk/exchange budgets "
    "use the default ladder", _pos)
DEF("query_timeout_s", 3600, "int",
    "per-statement deadline seconds (settable per session via SET "
    "query_timeout_s); checked host-side at result-boundary "
    "checkpoints — operator close, spill chunk, DTL slice join, the "
    "capacity-retry ladder — raising typed QueryTimeout", _pos)

# overload robustness: statement admission + fair queuing
# (server/admission.py)
DEF("enable_admission", True, "bool",
    "statement admission control: queries/DML check a per-tenant slot "
    "out before binding; over-limit statements wait in a bounded "
    "per-tenant FIFO granted by weighted round-robin across tenants, "
    "full queues reject fast with typed ServerBusy (≙ the tenant "
    "worker quota + large query queue)")
DEF("admission_slots", 32, "int",
    "process-wide concurrent admitted statements (0 disables "
    "admission)", _nonneg)
DEF("admission_tenant_slots", 16, "int",
    "per-tenant cap on concurrently admitted statements", _pos)
DEF("admission_queue_limit", 64, "int",
    "bounded per-tenant admission FIFO depth; statements beyond it "
    "reject immediately with ServerBusy", _nonneg)
DEF("admission_queue_timeout_s", 10.0, "float",
    "queue-wait budget before a queued statement gives up with "
    "ServerBusy (also clamped to the statement's own deadline)", _pos)
DEF("admission_tenant_weight", 1, "int",
    "weighted-round-robin share of this tenant's queue when admission "
    "slots free up (set on the tenant's config overlay)", _pos)
DEF("large_query_threshold_s", 5.0, "float",
    "observed runtime past which a statement yields its normal "
    "admission slot to the low-priority large-query lane at its next "
    "checkpoint (point queries stop starving behind scans)", _pos)
DEF("admission_large_slots", 2, "int",
    "concurrent statements of the low-priority large-query lane", _pos)

# overload robustness: memstore write backpressure
DEF("memstore_limit_bytes", 256 << 20, "cap",
    "per-tenant unflushed memstore byte budget; writes at the limit "
    "raise typed MemstoreFull until the freeze/flush catches up", _pos)
DEF("writing_throttle_trigger_pct", 60, "int",
    "percentage of memstore_limit_bytes past which writers pay a "
    "ramped sleep before each append (≙ "
    "writing_throttling_trigger_percentage)",
    lambda v: 1 <= v <= 100)
DEF("writing_throttle_max_sleep_s", 0.05, "float",
    "per-write sleep ceiling of the memstore throttle ramp", _pos)

# disk-pressure plane: per-surface byte budgets (0 = unlimited) +
# read-only degradation (server/diskmgr.py)
DEF("log_disk_limit_bytes", 0, "cap",
    "per-tenant PALF WAL directory budget; crossing the utilization "
    "threshold kicks checkpoint + WAL recycle, reaching the limit "
    "drops the tenant to read-only (typed TenantReadOnly on writes, "
    "reads keep serving) — ≙ log_disk_utilization_limit_threshold",
    _nonneg)
DEF("data_disk_limit_bytes", 0, "cap",
    "per-tenant data directory (segments + manifest + slog) budget; "
    "at the limit the tenant enters read-only until space frees",
    _nonneg)
DEF("spill_disk_limit_bytes", 0, "cap",
    "per-tenant temp-file (spill) byte budget; exhaustion kills only "
    "the spilling statement (typed SpillBudgetExceeded) — ≙ the "
    "tmp-file quota", _nonneg)
DEF("log_disk_utilization_threshold", 80, "int",
    "percentage of log_disk_limit_bytes past which the tenant "
    "reclaims aggressively (checkpoint + WAL recycle) before "
    "degrading, and back under which read-only auto-exits",
    lambda v: 1 <= v <= 100)

# PX / distributed
DEF("px_default_dop", 0, "int",
    "degree of parallelism (0 = mesh size)", _nonneg)
DEF("px_exchange_capacity_per_dest", 1 << 20, "int",
    "all_to_all per-destination row budget", _pos)
DEF("px_workers_per_tenant", 64, "int",
    "PX admission quota (≙ px_workers_per_cpu_quota)", _pos)
DEF("pdml_min_rows", 8192, "int",
    "parallel-DML threshold: statements writing at least this many rows "
    "fan the write phase out over tenant workers (≙ enable_parallel_dml "
    "+ the PDML DFO split, src/sql/engine/pdml)", _pos)
DEF("pdml_dop", 4, "int", "parallel-DML worker count", _pos)
DEF("enable_dtl_pushdown", True, "bool",
    "ship qualifying single-table partial plans to cluster nodes over "
    "the DTL exchange instead of scanning everything on the "
    "coordinator (≙ PX DFO scheduling onto data-owning servers)")
DEF("dtl_min_rows", 4096, "int",
    "minimum estimated base-table rows before a plan is considered for "
    "DTL pushdown (below it, per-node RPC overhead dominates)", _nonneg)

# robustness: fault injection + failure detection (net/faults.py,
# net/health.py)
DEF("enable_fault_injection", False, "bool",
    "allow the fault.inject/fault.clear admin RPC verbs to arm rules on "
    "this node's FaultPlane (≙ errsim tracepoints scoped to the rpc "
    "frame; scripts/chaos_bench.py nemesis schedules)")
DEF("fault_seed", 0, "int",
    "seed of the per-node FaultPlane rng — a failing nemesis schedule "
    "replays frame-for-frame", _nonneg)
DEF("health_ping_interval_s", 0.5, "float",
    "failure-detector heartbeat period per peer; detection latency is "
    "O(interval * health_down_threshold)", _pos)
DEF("health_suspect_threshold", 2, "int",
    "consecutive failures before a peer turns 'suspect' (PX slices "
    "pre-emptively route away from it)", _pos)
DEF("health_down_threshold", 4, "int",
    "consecutive failures before a peer turns 'down' (a dead leader "
    "triggers immediate re-election instead of lease expiry)", _pos)
DEF("rpc_conn_pool_size", 4, "int",
    "idle connections kept per RpcClient; calls beyond it dial extra "
    "sockets so control-plane pings never queue behind bulk transfers "
    "(LRU extras close on checkin)", _pos)
DEF("rpc_max_conns_per_peer", 16, "int",
    "hard cap on live sockets (idle + in-flight) per RpcClient; "
    "checkout past it waits for a checkin inside the call deadline and "
    "then fails with typed ConnPoolExhausted instead of growing "
    "without bound under fan-out load", _pos)

# storage
DEF("memstore_limit_rows", 1_000_000, "int",
    "freeze threshold per tablet (rows in active memtable)", _pos)
DEF("minor_compact_trigger", 4, "int",
    "L0 segment count triggering minor compaction (≙ minor_compact_trigger)",
    _pos)
DEF("major_compaction_interval_s", 86400, "int",
    "major merge cadence (≙ daily merge)", _pos)
DEF("segment_chunk_rows", 65536, "int",
    "rows per encoded chunk (micro-block analog)", _pos)
DEF("enable_zone_map_pruning", True, "bool",
    "skip chunks via min/max zone maps on range predicates")

# WAL / replication
DEF("wal_replica_count", 3, "int", "PALF replica count", _pos)
DEF("palf_lease_ms", 400, "int", "election lease duration", _pos)
DEF("log_checkpoint_interval_s", 60, "int",
    "periodic checkpoint cadence advancing the WAL replay point so "
    "restart replay cost is O(tail), not O(history)", _pos)
DEF("checkpoint_lag_entries", 256, "int",
    "minimum applied WAL entries past the persisted replay point "
    "before a periodic checkpoint bothers flushing", _nonneg)

# crash recovery / rebuild (net/rebuild.py, storage/recovery.py)
DEF("enable_auto_rebuild", True, "bool",
    "a node booting with NO local recovery sources (no manifest, slog "
    "or WAL) bootstraps from a peer's checkpoint + segments + WAL via "
    "the rebuild.fetch_* verbs (≙ replica rebuild ha_dag)")
DEF("rebuild_chunk_bytes", 4 << 20, "cap",
    "byte budget per rebuild.fetch_segments chunk", _pos)

# data integrity / scrub (storage/scrub.py, storage/integrity.py)
DEF("enable_scrub", True, "bool",
    "background scrubber: periodically re-read + checksum-verify every "
    "persisted segment, compare per-table logical digests across "
    "replicas (scrub.checksum verb, majority wins), and auto-repair "
    "corrupt/minority tables from a healthy peer over the chunked "
    "rebuild.fetch_* verbs (≙ replica checksum verification at major "
    "freeze) — surfaced as gv$scrub")
DEF("scrub_interval_s", 300.0, "float",
    "scrub round cadence; each round re-reads local segment files and "
    "exchanges per-table digests with peers — hot-reloadable (the loop "
    "re-reads it every wait)", _pos)
DEF("enable_disk_faults", False, "bool",
    "allow fault.inject where='disk' rules (seeded bitflip/truncate of "
    "just-persisted segment/manifest/slog/wal files) to arm on this "
    "node — the deterministic media-rot half of the chaos plane")

# tenants / resources
DEF("tenant_cpu_quota", 4, "int", "worker threads per tenant unit", _pos)
DEF("tenant_memory_limit", 4 << 30, "cap",
    "per-tenant memory budget in bytes", _pos)
DEF("enable_rate_limit", True, "bool",
    "memstore write backpressure (server/admission.py::"
    "MemstoreThrottle): account unflushed bytes per write, ramp writer "
    "sleeps past writing_throttle_trigger_pct of "
    "memstore_limit_bytes, raise MemstoreFull at the hard limit "
    "(≙ write throttling)")

# device-time profiling + roofline calibration (exec/plan.py split,
# server/calibrate.py, server/profiler.py)
DEF("enable_profiling", True, "bool",
    "host/device time split: execute_plan brackets block_until_ready() "
    "at the result boundary so every execution records host_s (bind + "
    "dispatch) and device_s (compute) separately — feeds gv$sql_audit "
    "host_s/device_s, gv$plan_cache achieved_gflops/achieved_gbps, the "
    "time q-error ledger, and the PROFILE deep trace; hot-reloadable "
    "via ALTER SYSTEM SET (scripts/profile_bench.py prices the toggle)")
DEF("enable_calibration", True, "bool",
    "roofline cost calibration (server/calibrate.py): run the "
    "canonical probe suite at first boot (constants persisted "
    "checksummed as cost_units.json, surfaced as gv$cost_units) and "
    "allow ALTER SYSTEM CALIBRATE re-probes; off = no machine "
    "constants, roofline predictions and time q-errors degrade to 0")

# diagnostics
DEF("enable_metrics", True, "bool",
    "cluster-wide metrics plane (server/metrics.py): named counters, "
    "gauges and log-bucketed latency histograms updated host-side at "
    "result/span-close boundaries, surfaced as gv$sysstat / "
    "gv$sysstat_histogram / SHOW METRICS and scraped cluster-wide over "
    "the metrics.scrape verb (≙ ob_diagnose_info sysstat counters)")
DEF("enable_query_trace", True, "bool",
    "full-link statement tracing (server/trace.py): a root span per "
    "statement, children across compile/execute/spill/exchange/rpc, "
    "remote halves shipped back with replies (≙ ObTrace/flt -> "
    "gv$ob_trace)")
DEF("trace_sample_rate", 1.0, "float",
    "fraction of statements whose trace tree is RETAINED in gv$trace "
    "(collection stays on; slow/failed statements always retain)", _frac)
DEF("trace_slow_threshold_s", 1.0, "float",
    "statements at least this slow keep their trace tree even when the "
    "sample draw said no (tail attribution must never be sampled away)",
    _nonneg)
DEF("trace_ring_spans", 20000, "int",
    "bounded per-node span ring capacity behind gv$trace", _pos)
DEF("enable_ash", True, "bool",
    "active-session-history sampling (≙ ASH)")
DEF("ash_sample_interval_ms", 1000, "int", "ASH sampling period", _pos)
DEF("sql_audit_queue_size", 10000, "int",
    "ring-buffer capacity of gv$sql_audit", _pos)
DEF("enable_defensive_check", True, "bool",
    "extra engine invariant checks (≙ _enable_defensive_check)")
DEF("kv_cache_limit_bytes", 2 << 30, "cap",
    "device-relation (block) cache budget per tenant "
    "(≙ ObKVGlobalCache memory limit)", _pos)
DEF("enable_dbms_jobs", False, "bool",
    "start the DBMS job scheduler thread at boot (stats auto-gather, "
    "auto compaction — ≙ dbms_scheduler maintenance windows)")
DEF("stats_gather_interval_s", 600.0, "float",
    "auto stats gather period", _pos)
DEF("auto_compact_interval_s", 3600.0, "float",
    "auto major-compaction period", _pos)
DEF("lock_wait_timeout_s", 5.0, "float",
    "implicit DML table-lock wait budget (≙ lock_wait_timeout)", _pos)

# workload diagnostics repository (server/workload.py) — persistent
# crc64-stamped snapshots of the observability surfaces, the substrate
# of ANALYZE WORKLOAD REPORT (≙ AWR-style workload repository).  All
# four knobs hot-reload: the snapshot loop re-reads them every round.
DEF("enable_workload_repo", False, "bool",
    "background workload-snapshot thread: periodically persist "
    "gv$sysstat + histograms, gv$time_model, plan-cache/plan-history "
    "summaries, ASH rollups and disk/health state to "
    "<data_dir>/workload/ (crc64-verified on load, quarantined on "
    "mismatch per the PR 9 integrity contract)")
DEF("workload_snapshot_interval_s", 60.0, "float",
    "cadence of automatic workload snapshots", _pos)
DEF("workload_retention_keep", 64, "int",
    "newest snapshots retained per node; older ones are pruned "
    "(count cap, mirrors integrity.prune_quarantine)", _pos)
DEF("workload_retention_max_age_s", 7 * 24 * 3600.0, "float",
    "snapshots older than this are pruned regardless of count", _pos)


class Config:
    """One configuration instance (cluster-level or tenant overlay)."""

    def __init__(self, persist_path: str | None = None,
                 parent: "Config | None" = None):
        self._values: dict[str, Any] = {}
        self._parent = parent
        self._persist_path = persist_path
        self._lock = threading.RLock()
        self._watchers: list[Callable[[str, Any], None]] = []
        if persist_path and os.path.exists(persist_path):
            with open(persist_path) as f:
                stored = json.load(f)
            for k, v in stored.items():
                if k in _DEFS:
                    self._values[k] = v

    # ------------------------------------------------------------------
    def get(self, name: str):
        if name not in _DEFS:
            raise KeyError(f"unknown parameter {name!r}")
        with self._lock:
            if name in self._values:
                return self._values[name]
        if self._parent is not None:
            return self._parent.get(name)
        return _DEFS[name].default

    def __getitem__(self, name):
        return self.get(name)

    def set(self, name: str, value):
        """Runtime update with type coercion + validation
        (≙ ALTER SYSTEM SET)."""
        d = _DEFS.get(name)
        if d is None:
            raise KeyError(f"unknown parameter {name!r}")
        value = _coerce(d.ptype, value)
        if d.validator is not None and not d.validator(value):
            raise ValueError(f"invalid value {value!r} for {name}")
        with self._lock:
            self._values[name] = value
            if self._persist_path:
                tmp = self._persist_path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(self._values, f, indent=1)
                os.replace(tmp, self._persist_path)
            watchers = list(self._watchers)
        for w in watchers:
            w(name, value)

    def watch(self, fn: Callable[[str, Any], None]):
        self._watchers.append(fn)

    def snapshot(self) -> dict:
        out = {}
        for name, d in sorted(_DEFS.items()):
            out[name] = self.get(name)
        return out

    @staticmethod
    def defs() -> dict[str, ParamDef]:
        return dict(_DEFS)


_CAP_UNITS = {"k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40}


def _coerce(ptype: str, v):
    if ptype == "int":
        return int(v)
    if ptype == "float":
        return float(v)
    if ptype == "bool":
        if isinstance(v, str):
            return v.lower() in ("1", "true", "on", "yes")
        return bool(v)
    if ptype == "cap":
        if isinstance(v, str) and v and v[-1].lower() in _CAP_UNITS:
            return int(float(v[:-1]) * _CAP_UNITS[v[-1].lower()])
        return int(v)
    return str(v)
