"""NodeServer: one observer process of a multi-node cluster.

Reference analog: ObServer (src/observer/ob_server.cpp:228) hosting the
rpc frame, log service, storage, and SQL for one server — reduced to the
sys tenant.  The replication plane is a networked PALF group
(palf/netcluster.py, ≙ palf_handle_impl receive_log RPCs); DDL and DML
redo both ride it, so every node converges to the same engine state.
Writes execute on the PALF leader (statement routing on OB_NOT_MASTER,
≙ DML retry via the location cache); strong reads from a follower route
to the leader; weak reads (`consistency='weak'`) run on the local
replica (≙ weak-consistency replica reads).  ``das.scan`` serves
chunk-streamed snapshot column fetches for remote-relation access
(≙ ObDataAccessService, src/sql/das/ob_data_access_service.h:21).

CLI:  python -m oceanbase_tpu.net.node --node-id 1 --port 7001 \
          --peers 2=127.0.0.1:7002,3=127.0.0.1:7003 --root /tmp/n1 \
          [--bootstrap]
"""

from __future__ import annotations

import argparse
import json
import threading
import time

import numpy as np

from oceanbase_tpu.net.faults import FaultPlane
from oceanbase_tpu.net.health import HealthMonitor
from oceanbase_tpu.net.rpc import RpcClient, RpcError, RpcServer
from oceanbase_tpu.palf.cluster import NoQuorum, NotLeader
from oceanbase_tpu.palf.netcluster import NetPalf
from oceanbase_tpu.server import admission as qadmission
from oceanbase_tpu.share.location import LocationCache
from oceanbase_tpu.storage.integrity import CorruptionError, arrays_crc

_DDL_KINDS = {"create_view", "drop_view",
              "create_table", "drop_table", "truncate", "alter_add",
              "alter_drop", "create_index", "drop_index", "aux_index",
              "drop_aux_index"}
_WRITE_PREFIXES = ("insert", "update", "delete", "replace", "create",
                   "drop", "alter", "truncate", "load", "begin",
                   "commit", "rollback", "xa")
SCAN_CHUNK_ROWS = 65536


class NodeDatabase:
    """Database facade for one node process: the attribute surface
    sessions touch (config, tx/engine routing, observability), bound to
    the node's sys tenant over the networked WAL."""

    def __init__(self, node, root):
        import itertools

        from oceanbase_tpu.px.dtl import DtlMetrics
        from oceanbase_tpu.server.monitor import (
            AshSampler,
            PlanFeedback,
            PlanHistory,
            PlanMonitor,
            SqlAudit,
            TimeModel,
            WaitEvents,
        )
        from oceanbase_tpu.server.trace import TraceRegistry
        from oceanbase_tpu.server.virtual_tables import VirtualTables

        self._node = node
        self.root = root
        self.config = node.config
        self.node_id = node.node_id  # stamps trace spans / gv$trace
        self.tenants = {"sys": node.tenant}
        self.workarea_history: list = []
        self.plan_monitor = PlanMonitor()
        self.plan_feedback = PlanFeedback(
            int(self.config["plan_feedback_entries"]))
        self.plan_history = PlanHistory(
            int(self.config["plan_history_entries"]))
        self.audit = SqlAudit(int(self.config["sql_audit_queue_size"]))
        self.wait_events = WaitEvents()
        self.time_model = TimeModel()  # gv$time_model (phase split)
        # ASH + full-link trace ring: NodeServer.start()/stop() drive
        # the sampler lifecycle; sessions register their state slots in
        # Session.__init__ like they do against a plain Database
        self.ash = AshSampler(
            interval_s=int(self.config["ash_sample_interval_ms"])
            / 1000.0)
        self.trace_registry = TraceRegistry(
            int(self.config["trace_ring_spans"]))
        self.dtl_metrics = DtlMetrics()
        self.dtl = None  # DtlExchange, installed by NodeServer
        self.health = None  # HealthMonitor, installed by NodeServer
        self.scrub = None  # ScrubState, installed by NodeServer
        # overload plane: statement admission + KILL for the sessions
        # this node's wire threads run (one sys tenant per node)
        from oceanbase_tpu.server.admission import AdmissionController

        self.admission = AdmissionController(
            self.config,
            weight_of=lambda name: int(
                self.config["admission_tenant_weight"]))
        self.virtual_tables = VirtualTables(self)
        self._session_ids = itertools.count(1)
        # workload diagnostics repository: NodeServer installs the
        # fault plane on self.faults first, then start() launches the
        # snapshot thread beside scrub/hb/ckpt
        from oceanbase_tpu.server.workload import WorkloadRepository

        self.workload = WorkloadRepository(self, root)

    @property
    def tx(self):
        return self._node.tx

    @property
    def engine(self):
        return self._node.engine

    @property
    def catalog(self):
        return self._node.catalog

    def create_tenant(self, *a, **kw):
        raise NotImplementedError(
            "tenant DDL is a rootservice operation; run it on the "
            "cluster bootstrap node")

    drop_tenant = create_tenant


class NodeServer:
    def __init__(self, node_id: int, host: str, port: int,
                 peers: dict[int, tuple[str, int]],
                 root: str | None = None, bootstrap: bool = False,
                 lease_ms: int = 2000):
        import os

        from oceanbase_tpu.server.config import Config
        from oceanbase_tpu.server.tenant import Tenant

        self.node_id = node_id
        self.root = root
        self.peer_addrs = dict(peers)
        self.config = Config(persist_path=(
            os.path.join(root, "config.json") if root else None))
        # metrics plane on/off rides the config (ALTER SYSTEM SET
        # enable_metrics — scripts/metrics_bench.py prices the toggle)
        from oceanbase_tpu.server import metrics as _qmetrics

        _qmetrics.set_enabled(bool(self.config["enable_metrics"]))
        self.config.watch(
            lambda k, v: _qmetrics.set_enabled(bool(v))
            if k == "enable_metrics" else None)
        # per-process fault plane: every frame this node sends or
        # receives consults it (seeded — nemesis schedules replay)
        self.faults = FaultPlane(seed=int(self.config["fault_seed"]))
        pool = int(self.config["rpc_conn_pool_size"])
        max_conns = int(self.config["rpc_max_conns_per_peer"])
        self.peers = {pid: RpcClient(h, p, peer_id=pid,
                                     local_id=node_id,
                                     faults=self.faults, pool_size=pool,
                                     max_conns=max_conns)
                      for pid, (h, p) in peers.items()}
        self._apply_lock = threading.RLock()

        # rebuild tier: a WIPED node (no manifest, no slog, no WAL)
        # bootstraps from a peer's checkpoint + segments + WAL BEFORE
        # the engine opens, then boots through the ordinary restart
        # path (≙ ob_storage_ha_dag replica rebuild).  The whole boot
        # runs under one trace so gv$trace shows the recovery tree
        # (rebuild.fetch / recovery.replay / recovery.restore_prepared).
        import uuid

        from oceanbase_tpu.net import rebuild as _rebuild
        from oceanbase_tpu.server import trace as qtrace
        from oceanbase_tpu.storage.recovery import RecoveryState

        self.recovery = RecoveryState(node_id)
        boot_trace = qtrace.TraceCtx(
            f"boot-{node_id}-{uuid.uuid4().hex[:8]}", node=node_id)
        with qtrace.activate(boot_trace):
            if root:
                # baseline integrity is NOT gated by the rebuild knob:
                # a digest-failing manifest/slog pair quarantines here
                # regardless, so boot falls back to WAL replay instead
                # of trusting (or crashing on) rot
                _rebuild.quarantine_corrupt_baseline(
                    root, recovery=self.recovery)
            if root and bool(self.config["enable_auto_rebuild"]):
                _rebuild.maybe_rebuild(
                    root, node_id, self.peers, recovery=self.recovery,
                    chunk_bytes=int(self.config["rebuild_chunk_bytes"]))

            wal_dir = os.path.join(root, "wal") if root else None
            self.palf = NetPalf(node_id, self.peers, log_dir=wal_dir,
                                apply_cb=self._apply_entry,
                                lease_ms=lease_ms,
                                recovery=self.recovery)
            # quarantine policy: a cluster node has peers to refetch a
            # checksum-failing segment from, so boot quarantines and
            # the scrub plane repairs instead of failing the boot
            self.tenant = Tenant("sys", root, self.config,
                                 wal=self.palf, recovery=self.recovery,
                                 corrupt_policy="quarantine")
        self.engine = self.tenant.engine
        # persistence boundaries consult the disk-fault plane (seeded
        # bitflip/truncate of just-written files; gated at arm time by
        # enable_disk_faults in _h_fault_inject)
        self.engine.faults = self.faults
        self.palf.replica.faults = self.faults
        # disk-pressure degradation hooks: entering read-only hands
        # PALF leadership to a peer with headroom (writes land there);
        # exiting needs no action — the location cache re-learns
        self.tenant.diskmgr.on_readonly = self._on_disk_readonly
        self.tx = self.tenant.tx
        self.catalog = self.tenant.catalog
        # replicate logical DDL through the log stream (followers apply
        # in _apply_entry; physical segment ops stay node-local)
        self.engine.ddl_wal_cb = self._on_local_ddl
        self.db = NodeDatabase(self, root)
        # backup/spill writers reach the fault plane through the db
        self.db.faults = self.faults
        if boot_trace.spans:
            self.db.trace_registry.add(boot_trace.snapshot())
        from oceanbase_tpu.px.dtl import DtlExchange

        self.db.dtl = DtlExchange(self, self.db.dtl_metrics)
        self.location = LocationCache(node_id, self.peers,
                                      self.palf._on_state)
        # failure detector: heartbeats + per-call outcomes feed the
        # three-state breaker; a dead leader triggers re-election
        self.health = HealthMonitor(
            node_id, self.peers,
            interval_s=float(self.config["health_ping_interval_s"]),
            suspect_after=int(self.config["health_suspect_threshold"]),
            down_after=int(self.config["health_down_threshold"]),
            on_down=self._on_peer_down)
        for pid, cli in self.peers.items():
            cli.observer = self.health.observer(pid)
        self.db.health = self.health

        self.rebuild = _rebuild.RebuildServer(self)
        from oceanbase_tpu.storage.scrub import Scrubber

        self.scrubber = Scrubber(self)
        self.db.scrub = self.scrubber.state
        from oceanbase_tpu.px.dtl import CancelRegistry

        self.dtl_cancels = CancelRegistry()
        handlers = {
            "ping": lambda: "pong",
            "das.scan": self._h_scan,
            "das.pull": self._h_pull,
            "dtl.execute": self._h_dtl_execute,
            "dtl.cancel": self._h_dtl_cancel,
            "sql.execute": self._h_execute,
            "node.state": self._h_state,
            "cluster.health": self._h_health,
            "recovery.state": self._h_recovery,
            "metrics.scrape": self._h_metrics,
            "workload.snapshot": self._h_workload_snapshot,
            "fault.inject": self._h_fault_inject,
            "fault.clear": self._h_fault_clear,
            "config.set": self._h_config_set,
            "scrub.checksum": self.scrubber.checksum_handler,
            "scrub.run": self._h_scrub_run,
            "disk.takeover": self._h_disk_takeover,
            **self.rebuild.handlers(),
            **self.palf.handlers(),
        }
        self.server = RpcServer(host, port, handlers,
                                faults=self.faults, node_id=node_id)
        self._sessions: dict = {}
        self._stop = threading.Event()
        self._hb: threading.Thread | None = None
        self._ckpt: threading.Thread | None = None
        self._bootstrap = bootstrap

    # ------------------------------------------------------------------
    # WAL apply (follower replay; ≙ replayservice)
    # ------------------------------------------------------------------
    def _apply_entry(self, entry):
        with self._apply_lock:
            if entry.lsn in self.palf.local_lsns:
                # leader-originated: the write path already applied it
                self.palf.local_lsns.discard(entry.lsn)
                return
            try:
                rec = json.loads(entry.payload.decode())
            except Exception:
                return
            # the tx service's PERSISTENT replay buffers: boot replay
            # leftovers (e.g. a prepared XA branch's redo) stay visible
            # to a commit record arriving later through catch-up, and a
            # replayed prepare record registers the branch for XA
            # RECOVER on this node too (durable XA across failover)
            max_ts = self.tx.apply_replay([entry])
            if rec.get("op") == "ddl":
                self.catalog.schema_version += 1
            if max_ts:
                self.tx.gts.advance_to(max_ts)

    def _on_local_ddl(self, op: dict):
        """Engine slog hook: replicate logical DDL when leading (a
        follower reaching here is applying REMOTE ddl — don't re-ship)."""
        if op.get("op") not in _DDL_KINDS:
            return
        if not self.palf.is_leader:
            return
        self.palf.append([json.dumps({"op": "ddl", "slog": op}).encode()])

    # ------------------------------------------------------------------
    # RPC handlers
    # ------------------------------------------------------------------
    def _h_state(self):
        return {"node_id": self.node_id,
                "tables": sorted(t for t in self.engine.tables
                                 if not t.startswith("__idx__")),
                "gts": self.tx.gts.current(),
                **self.palf._on_state()}

    def _h_health(self):
        """Failure-detector snapshot (the wire face of
        gv$cluster_health)."""
        return {"node_id": self.node_id,
                "peers": self.health.snapshot()}

    def _h_metrics(self, format: str = "wire"):
        """One node's metrics snapshot (the wire face of gv$sysstat /
        gv$sysstat_histogram).  ``format="prom"`` returns Prometheus
        text exposition instead of the mergeable wire body."""
        from oceanbase_tpu.server import metrics as qmetrics

        if format == "prom":
            return {"node_id": self.node_id,
                    "text": qmetrics.prom_text()}
        return {"node_id": self.node_id,
                "wire": qmetrics.wire_snapshot()}

    def _h_workload_snapshot(self):
        """This node's LOCAL workload-diagnostics payload (the wire
        face of the snapshot merge): a pure read of monotonic counters
        plus point-in-time state, digest-stamped so the merging
        coordinator can verify the bulk body before folding it in."""
        from oceanbase_tpu.server.workload import canonical_bytes
        from oceanbase_tpu.storage.integrity import bytes_crc

        payload = self.db.workload.collect()
        return {"node_id": self.node_id,
                "payload": payload,
                "crc": bytes_crc(canonical_bytes(payload))}

    def _h_recovery(self):
        """Recovery progress (the wire face of gv$recovery): boot
        replay / rebuild / checkpoint events plus the live catch-up
        lag and the prepared XA branches this node can recover."""
        r = self.palf.replica
        xids = self.tx.recoverable_xids()
        return {"node_id": self.node_id,
                "applied_lsn": r.applied_lsn,
                "committed_lsn": r.committed_lsn,
                "replay_point": self.engine.meta.get("wal_lsn", 0),
                "prepared_xids": xids,
                "events": self.recovery.rows()}

    def _h_fault_inject(self, where: str, action: str, verb=None,
                        peer=None, prob: float = 1.0, nth=None,
                        count: int = -1, delay_ms: float = 0.0,
                        seed=None):
        """Admin verb arming one FaultPlane rule on THIS node (≙ ALTER
        SYSTEM SET ... errsim tracepoints; gated by config so a stray
        client cannot chaos a production cluster)."""
        if not bool(self.config["enable_fault_injection"]):
            raise PermissionError(
                "fault injection disabled: alter system set "
                "enable_fault_injection = true first")
        if where == "disk" and not bool(self.config["enable_disk_faults"]):
            raise PermissionError(
                "disk faults disabled: alter system set "
                "enable_disk_faults = true first")
        rid = self.faults.inject(where, action, verb=verb, peer=peer,
                                 prob=prob, nth=nth, count=count,
                                 delay_ms=delay_ms, seed=seed)
        return {"rule_id": rid, "node_id": self.node_id}

    def _h_fault_clear(self, rule_id=None):
        return {"removed": self.faults.clear(rule_id),
                "node_id": self.node_id}

    def _h_config_set(self, name: str, value):
        """Admin verb: set one config knob on THIS node (≙ ALTER
        SYSTEM SET ... SERVER 'ip:port', which scopes a change to a
        single observer).  SQL ALTER SYSTEM routes to the leader, so
        retuning a specific replica — e.g. lifting the log budget on
        a demoted, disk-pressured node — needs the node-scoped path.
        A disk-budget change polls the disk manager immediately:
        budget crossings (and read-only auto-exit) must not ride out
        the checkpoint-loop cadence."""
        self.config.set(str(name), value)
        if str(name).endswith("_disk_limit_bytes"):
            self.tenant.diskmgr.poll(force=True)
        return {"node_id": self.node_id, "name": str(name),
                "read_only": bool(self.tenant.diskmgr.read_only)}

    def _h_scrub_run(self):
        """Admin verb: run one scrub round NOW (detect → quarantine →
        repair → parity) and return its summary — the periodic loop's
        cadence is for production, benches/tests want determinism."""
        return self.scrubber.run_once()

    def _on_peer_down(self, pid: int):
        """Failure-detector down transition: stop routing at the dead
        peer, and if it was the leader, campaign NOW instead of letting
        writes ride out the remaining lease (≙ election priority takeover
        on server blacklist events)."""
        self.location.invalidate()
        if not self._stop.is_set():
            self.palf.on_peer_down(pid)

    def _on_disk_readonly(self, surface: str):
        """Read-only entry hook (server/diskmgr): if this node leads
        the PALF group, hand leadership to a peer with log-disk
        headroom so cluster writes keep landing somewhere — the
        relinquish runs OFF the write path (the hook fires inside a
        failing writer's poll)."""
        if not self.palf.is_leader or self._stop.is_set():
            return

        def _relinquish():
            for pid in sorted(self.peers):
                qadmission.checkpoint()  # KILL/deadline between peers
                try:
                    if self.peers[pid].call("disk.takeover",
                                            from_node=self.node_id):
                        self.location.invalidate()
                        return
                except OSError:
                    continue

        threading.Thread(target=_relinquish, daemon=True).start()

    def _h_disk_takeover(self, from_node=None):
        """A disk-pressured leader asks THIS node to campaign.  Refuse
        when our own log surface is degraded (shifting leadership onto
        another full disk helps nobody); otherwise run one election —
        winning demotes the pressured leader via the term bump."""
        dm = self.tenant.diskmgr
        dm.poll(force=True)
        if dm.read_only or dm.state("log") in ("pressure", "full"):
            return False
        try:
            self.palf.elect()
            return True
        except (NoQuorum, OSError):
            return False

    def _h_scan(self, table: str, snapshot: int | None = None,
                offset: int = 0, limit: int = SCAN_CHUNK_ROWS):
        """One chunk of a snapshot scan; the caller pages via
        offset/limit (streamed batches, ≙ the DAS scan iterator)."""
        ts = self.engine.tables.get(table)
        if ts is None:
            raise KeyError(f"table {table} not on node {self.node_id}")
        snap = int(snapshot) if snapshot else self.tx.gts.current()
        arrays, valids = ts.tablet.snapshot_arrays(snap)
        n = len(next(iter(arrays.values()))) if arrays else 0
        s, e = min(offset, n), min(offset + limit, n)
        out_arrays = {k: np.asarray(v)[s:e] for k, v in arrays.items()}
        out_valids = {k: np.asarray(v)[s:e]
                      for k, v in valids.items() if v is not None}
        return {
            "snapshot": snap, "total": n,
            "arrays": out_arrays,
            "valids": out_valids,
            # per-chunk digest over the bytes that actually ship; the
            # client verifies each page before concatenating
            "crc": arrays_crc(out_arrays, out_valids),
            "types": {c.name: [c.dtype.kind.value, c.dtype.precision or 0,
                               c.dtype.scale or 0]
                      for c in ts.tdef.columns},
        }

    def _h_pull(self, table: str, node_id: int | None = None):
        """Pull a table's full snapshot from a peer via the legacy
        das.scan paging (the path DTL pushdown replaces) and report its
        wire cost — the pushdown-vs-pull comparison surface used by
        scripts/dtl_bench.py; the pull is recorded as a mode='pull' row
        in gv$px_exchange by fetch_remote_table."""
        stats: dict = {}
        arrays, _valids, _types, snap = self.fetch_remote_table(
            table, node_id=node_id, stats=stats)
        n = len(next(iter(arrays.values()))) if arrays else 0
        return {"rows": n, "snapshot": snap,
                "bytes": stats.get("bytes", 0), "node": self.node_id}

    def _h_dtl_cancel(self, token: str):
        """Idempotent fragment cancellation (the remote half of KILL /
        query timeout): set — or tombstone — the cancel flag for
        ``token``; a running fragment observes it at its next host-side
        result-boundary checkpoint, a late-arriving one aborts before
        scanning anything."""
        return {"already": self.dtl_cancels.cancel(str(token)),
                "node_id": self.node_id}

    def _h_dtl_execute(self, plan: dict, table: str, snapshot: int,
                       part: int = 0, nparts: int = 1,
                       applied_lsn: int = 0, with_ops: bool = False,
                       monitor_lanes: bool = False,
                       cancel_token: str = ""):
        """Execute one DTL partial-plan slice against the local replica
        (≙ the SQC running its DFO over local tablets and streaming
        exchange rows back; px/dtl.py holds the plan wire codec).

        ``applied_lsn`` is the coordinator's WAL apply point when it
        chose the snapshot: a replica behind it may be missing rows
        visible at ``snapshot``, so it refuses and the coordinator runs
        the slice on its own replica instead; a replica AHEAD is fine —
        the MVCC snapshot filter hides any newer versions."""
        from oceanbase_tpu.px import dtl

        ts = self.engine.tables.get(table)
        if ts is None:
            raise KeyError(f"table {table} not on node {self.node_id}")
        if self.palf.replica.applied_lsn < int(applied_lsn):
            raise dtl.DtlLagging(
                f"node {self.node_id} applied lsn "
                f"{self.palf.replica.applied_lsn} < {applied_lsn}")
        from oceanbase_tpu.server import trace as qtrace

        # coordinator-propagated cancellation: the fragment runs under a
        # RemoteCtx observing the token's flag, so execute_plan's
        # result-boundary checkpoints stop remote work too (and a
        # tombstoned token aborts before scanning anything)
        from oceanbase_tpu.server import admission as qadmission

        rctx = None
        pinned = ""
        if cancel_token:
            # pin for the fragment's whole execution: an LRU eviction
            # while RUNNING would hand dtl.cancel a fresh Event the
            # fragment's RemoteCtx never observes
            ev = self.dtl_cancels.pin(str(cancel_token))
            pinned = str(cancel_token)
            if ev.is_set():
                self.dtl_cancels.unpin(pinned)
                raise qadmission.QueryKilled(
                    f"fragment {cancel_token} cancelled before start")
            rctx = qadmission.RemoteCtx(ev, token=str(cancel_token))
        # monitor_lanes is the COORDINATOR's monitor-knob state: it
        # picks the fragment executable variant here, so the per-query
        # sampling decision (with_ops) never alternates the compile key
        # (see dtl.execute_fragment's monitor_lanes contract).
        # A local (coordinator-thread) call arrives WITHOUT a token and
        # must keep the statement's own ctx active — never mask it.
        import contextlib

        try:
            with (qadmission.activate(rctx) if rctx is not None
                  else contextlib.nullcontext()):
                with qtrace.span("dtl.fragment", table=table,
                                 part=int(part)) as sp:
                    out = dtl.execute_fragment(
                        ts, plan, int(snapshot), int(part), int(nparts),
                        with_ops=bool(with_ops),
                        monitor_lanes=bool(monitor_lanes))
                    sp.tags.update(rows=out["rows"],
                                   scanned=out["scanned"])
                    return out
        finally:
            if pinned:
                self.dtl_cancels.unpin(pinned)

    def _h_execute(self, sql: str, consistency: str = "strong",
                   session_id: int = 0, forwarded: bool = False):
        return self.execute(sql, consistency=consistency,
                            session_id=session_id, _forwarded=forwarded)

    # ------------------------------------------------------------------
    # SQL surface
    # ------------------------------------------------------------------
    def _session(self, session_id: int = 0):
        from oceanbase_tpu.sql.session import Session

        s = self._sessions.get(session_id)
        if s is None:
            # concurrent wire threads race the check-then-insert; the
            # apply lock makes one session per id authoritative
            with self._apply_lock:
                s = self._sessions.get(session_id)
                if s is None:
                    s = Session(self.catalog, tenant=self.tenant,
                                db=self.db)
                    self._sessions[session_id] = s
        return s

    @staticmethod
    def _is_write(sql: str) -> bool:
        return sql.lstrip().lower().startswith(_WRITE_PREFIXES)

    def execute(self, sql: str, consistency: str = "strong",
                session_id: int = 0, _forwarded: bool = False) -> dict:
        """-> {names, arrays, valids, rowcount, types, node}."""
        if self.palf.is_leader:
            return self._run_local(sql, session_id)
        if not self._is_write(sql) and consistency != "strong":
            return self._run_local(sql, session_id)  # weak local read
        if _forwarded:
            # a peer believed we lead but we don't — make it retry
            raise NotLeader(f"node {self.node_id} is not the leader")
        return self._forward(sql, consistency, session_id)

    def _run_local(self, sql: str, session_id: int) -> dict:
        s = self._session(session_id)
        res = s.execute(sql)
        arrays, valids = {}, {}
        for name in res.names:
            arrays[name] = np.asarray(res.arrays[name])
            v = res.valids.get(name)
            if v is not None:
                valids[name] = np.asarray(v)
        return {"names": list(res.names), "arrays": arrays,
                "valids": valids, "rowcount": int(res.rowcount),
                # result digest: forwarded statements ride the wire
                # back, and the forwarding node verifies before handing
                # rows to the session (local callers just ignore it)
                "crc": arrays_crc(arrays, valids),
                "types": {n: [t.kind.value, t.precision or 0,
                              t.scale or 0]
                          for n, t in res.dtypes.items()
                          if t is not None},
                "node": self.node_id}

    def _forward(self, sql: str, consistency: str, session_id: int):
        """Route to the leader; campaign ourselves when none is
        reachable (≙ OB_NOT_MASTER retry + failover)."""
        last_err: Exception | None = None
        for _attempt in range(4):
            qadmission.checkpoint()  # KILL/deadline between route tries
            target = self.location.leader()
            if target is None or target == self.node_id:
                try:
                    self.palf.elect()
                except NoQuorum as e:
                    last_err = e
                    time.sleep(0.25)
                    continue
                return self._run_local(sql, session_id)
            try:
                # safe despite the retry loop: the request_sent guard
                # below refuses to re-route once the statement may have
                # reached the old leader's wire
                res = self.peers[target].call(  # obcheck: ok(rpc.nonidempotent-resend)
                    "sql.execute", sql=sql, consistency=consistency,
                    session_id=(self.node_id << 32) | session_id,
                    forwarded=True)
                self._verify_result(res, target)
                return res
            except (OSError, RpcError) as e:
                if isinstance(e, RpcError) and e.kind not in (
                        "NotLeader", "NoQuorum"):
                    raise
                if getattr(e, "request_sent", False):
                    # the statement hit the wire and the reply was lost:
                    # the DML may have applied on the old leader, so a
                    # blind re-route could double-apply — surface the
                    # transport error to the session layer instead
                    raise
                last_err = e
                self.location.invalidate()
                time.sleep(0.25)
        raise NotLeader(f"no reachable leader: {last_err}")

    def _verify_result(self, res: dict, peer: int):
        """Digest check of a forwarded-statement reply (the sql twin of
        dtl.verify_reply)."""
        crc = res.get("crc")
        if crc is None:
            return  # pre-integrity peer build
        got = arrays_crc(res.get("arrays", {}), res.get("valids", {}))
        if got != crc:
            raise CorruptionError(
                f"sql.execute reply digest mismatch (peer {peer})",
                kind="sql")

    # ------------------------------------------------------------------
    # remote-relation fetch (DAS client side)
    # ------------------------------------------------------------------
    def fetch_remote_table(self, table: str, node_id: int | None = None,
                           snapshot: int | None = None,
                           stats: dict | None = None):
        """Stream a table's snapshot from its home node in chunks
        -> (arrays, valids, types, snapshot).  ``stats`` (optional dict)
        receives the exact wire cost: {"bytes", "rows"}."""
        import time as _time

        if node_id is None:
            node_id = self.location.home_of(table)
        cli = self.peers.get(node_id)
        if cli is None:
            # the table's home is this node (or unknown): serve the
            # local snapshot through the same handler instead of a
            # KeyError masquerading as an RpcError
            return self._local_table_pages(table, snapshot, stats)
        chunks = []
        snap, off, nbytes = snapshot, 0, 0
        t0 = _time.time()       # record timestamp (wall)
        m0 = _time.monotonic()  # elapsed source (step-proof)
        while True:
            qadmission.checkpoint()  # KILL/deadline between pages
            r, sent, recv = cli.call_with_size(
                "das.scan", table=table, snapshot=snap,
                offset=off, limit=SCAN_CHUNK_ROWS)
            if r.get("crc") is not None and \
                    arrays_crc(r["arrays"], r.get("valids", {})) \
                    != r["crc"]:
                raise CorruptionError(
                    f"das.scan chunk digest mismatch (table {table}, "
                    f"peer {node_id}, offset {off})", kind="das")
            nbytes += sent + recv
            snap = r["snapshot"]
            chunks.append(r)
            off += SCAN_CHUNK_ROWS
            if off >= r["total"]:
                break
        arrays, valids = {}, {}
        for k in chunks[0]["arrays"]:
            arrays[k] = np.concatenate([c["arrays"][k] for c in chunks])
        for k in chunks[0].get("valids", {}):
            valids[k] = np.concatenate([c["valids"][k] for c in chunks])
        if stats is not None:
            stats["bytes"] = nbytes
            stats["rows"] = chunks[0]["total"]
        metrics = getattr(self.db, "dtl_metrics", None)
        if metrics is not None:
            from oceanbase_tpu.px.dtl import DtlRecord

            metrics.record(DtlRecord(
                ts=t0, table=table, mode="pull", parts=1,
                pushdown_hit=False, bytes_shipped=nbytes,
                rows_shipped=chunks[0]["total"],
                elapsed_s=_time.monotonic() - m0))
        return arrays, valids, chunks[0]["types"], snap

    def _local_table_pages(self, table: str, snapshot: int | None,
                           stats: dict | None):
        """fetch_remote_table's local twin: page the snapshot through
        the same das.scan handler (zero wire bytes)."""
        chunks, snap, off = [], snapshot, 0
        while True:
            r = self._h_scan(table, snapshot=snap, offset=off,
                             limit=SCAN_CHUNK_ROWS)
            snap = r["snapshot"]
            chunks.append(r)
            off += SCAN_CHUNK_ROWS
            if off >= r["total"]:
                break
        arrays, valids = {}, {}
        for k in chunks[0]["arrays"]:
            arrays[k] = np.concatenate([c["arrays"][k] for c in chunks])
        for k in chunks[0].get("valids", {}):
            valids[k] = np.concatenate([c["valids"][k] for c in chunks])
        if stats is not None:
            stats["bytes"] = 0
            stats["rows"] = chunks[0]["total"]
        return arrays, valids, chunks[0]["types"], snap

    # ------------------------------------------------------------------
    def start(self):
        self.server.start()
        self._hb = threading.Thread(target=self._heartbeat, daemon=True)
        self._hb.start()
        self._ckpt = threading.Thread(target=self._checkpoint_loop,
                                      daemon=True)
        self._ckpt.start()
        self._scrub = threading.Thread(target=self._scrub_loop,
                                       daemon=True)
        self._scrub.start()
        self.health.start()
        if bool(self.config["enable_ash"]):
            self.db.ash.start()
        # workload snapshot thread: always launched (the loop gates on
        # enable_workload_repo every round, so ALTER SYSTEM turns it
        # on/off without a restart)
        self.db.workload.start()
        if self._bootstrap:
            threading.Thread(target=self._bootstrap_elect,
                             daemon=True).start()

    def _bootstrap_elect(self):
        """Campaign until a majority of peers is reachable (cluster
        bootstrap, ≙ rootservice bootstrap electing the first leader)."""
        while not self._stop.is_set():
            try:
                if self.location.leader() is not None:
                    return
                self.palf.elect()
                return
            except NoQuorum:
                time.sleep(0.3)

    def _heartbeat(self):
        period = self.palf.proposer.lease_ms / 4000.0
        while not self._stop.wait(period):
            try:
                if self.palf.replica.role == "leader":
                    self.palf.tick()
            except Exception:
                pass

    def _checkpoint_loop(self):
        """Periodic replay-point advance (≙ the tenant checkpoint slog
        recycler): restart replay cost stays O(WAL tail since the last
        checkpoint), not O(history).  Skips quiet intervals — a
        checkpoint only runs once the local APPLY point is at least
        ``checkpoint_lag_entries`` past the persisted replay point."""
        while not self._stop.wait(
                float(self.config["log_checkpoint_interval_s"])):
            try:
                lag = (self.palf.replica.applied_lsn
                       - int(self.engine.meta.get("wal_lsn", 0)))
                if lag >= int(self.config["checkpoint_lag_entries"]):
                    self.tenant.checkpoint()
            except Exception:
                pass  # transient flush failure: retry next interval
            try:
                # disk-pressure poll rides the same cadence: budget
                # crossings degrade (and read-only auto-exits) even on
                # a node receiving no writes
                self.tenant.diskmgr.poll()
            except Exception:
                pass

    def _scrub_loop(self):
        """Periodic scrub rounds (storage/scrub.py): local re-verify,
        cross-replica digest vote, auto-repair.  The knob pair is read
        live — the wait ticks at most 1 s at a time so ALTER SYSTEM SET
        scrub_interval_s retunes the cadence without riding out a long
        in-flight sleep."""
        last = time.monotonic()
        while not self._stop.wait(
                min(float(self.config["scrub_interval_s"]), 1.0)):
            try:
                if time.monotonic() - last < \
                        float(self.config["scrub_interval_s"]):
                    continue
                last = time.monotonic()
                if bool(self.config["enable_scrub"]):
                    self.scrubber.run_once()
            except Exception:
                pass  # transient (peer churn mid-round): next round

    def stop(self):
        self._stop.set()
        self.db.workload.stop()
        self.db.ash.stop()
        self.health.stop()
        self.server.stop()
        self.palf.close()

    @property
    def port(self) -> int:
        return self.server.port


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--node-id", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--peers", default="",
                    help="id=host:port,id=host:port")
    ap.add_argument("--root", default=None)
    ap.add_argument("--bootstrap", action="store_true")
    ap.add_argument("--lease-ms", type=int, default=2000)
    args = ap.parse_args(argv)
    peers = {}
    for part in filter(None, args.peers.split(",")):
        pid, addr = part.split("=")
        h, p = addr.rsplit(":", 1)
        peers[int(pid)] = (h, int(p))
    node = NodeServer(args.node_id, args.host, args.port, peers,
                      root=args.root, bootstrap=args.bootstrap,
                      lease_ms=args.lease_ms)
    node.start()
    print(f"node {args.node_id} listening on {args.host}:{node.port}",
          flush=True)
    try:
        # CLI foreground idle: KeyboardInterrupt IS the cancel path
        while True:  # obcheck: ok(cancel.loop-no-checkpoint)
            time.sleep(3600)
    except KeyboardInterrupt:
        node.stop()


if __name__ == "__main__":
    main()
