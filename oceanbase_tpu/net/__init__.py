"""Host RPC plane for multi-node operation.

Reference analog: the rpc frame (deps/oblib/src/rpc — obrpc proxy codegen
over libeasy/pnio reactors) carrying PALF replication
(src/logservice/palf/palf_handle_impl.cpp:3235 receive_log), DAS remote
table access (src/sql/das/ob_data_access_service.h:21), and the location
service (src/share/location_cache/ob_location_service.h:27).

TPU-first split: the COMPUTE plane stays XLA collectives over ICI (px/);
this package is the HOST control/data plane between OS processes — python
sockets + a binary column codec stand in for obrpc, carrying redo logs,
snapshot scans, and SQL routing between nodes.
"""

from oceanbase_tpu.net.codec import decode_msg, encode_msg
from oceanbase_tpu.net.faults import FaultDrop, FaultPlane, FaultReset
from oceanbase_tpu.net.health import HealthMonitor
from oceanbase_tpu.net.rpc import (
    DeadlineExceeded,
    ProtocolError,
    RpcClient,
    RpcError,
    RpcServer,
    VerbPolicy,
    verb_policy,
)

__all__ = ["encode_msg", "decode_msg", "RpcServer", "RpcClient",
           "RpcError", "ProtocolError", "DeadlineExceeded",
           "VerbPolicy", "verb_policy", "FaultPlane", "FaultDrop",
           "FaultReset", "HealthMonitor"]
