"""Wiped-replica rebuild: bootstrap a node from a peer's checkpoint +
WAL, then let ordinary catch-up finish the job.

Reference analog: the replica rebuild / migration dag-nets
(src/storage/high_availability/ob_storage_ha_dag.h,
ob_ls_migration_handler) — a new or wiped replica copies a consistent
baseline (tablet metas + macro blocks ≙ manifest + segment files) from a
source replica, then replays the log tail.

Protocol (server side registered on every NodeServer):

    rebuild.fetch_meta()
        -> {"node_id", "wal_lsn", "role", "manifest": bytes,
            "slog": bytes, "files": [{"name", "size",
            "kind": "data" | "wal"}]}
        The peer checkpoints first and ships the manifest + slog BYTES
        inline (atomic with the file list — a checkpoint racing the
        chunked downloads cannot hand the client a NEWER manifest whose
        segments were never listed).  The listed segment files are
        immutable once written and never deleted; the WAL file is
        append-only — a chunk boundary racing an append at worst tears
        the final entry, which the torn-tail scan at boot truncates and
        catch-up re-ships.

    rebuild.fetch_segments(name, offset, limit)
        -> {"data": bytes, "eof": bool, "size": int, "crc": int}
        One chunk of one baseline file (byte-accounted, idempotent —
        the retry budget in net/rpc.py::POLICIES applies).  Every chunk
        carries a crc64 the client verifies BEFORE writing (a corrupt
        chunk re-fetches, bounded); listed data files additionally carry
        a whole-file crc in fetch_meta, re-verified after assembly —
        corrupt bytes are never installed.

Client side (``maybe_rebuild``) runs BEFORE the tenant boots: files
download into ``<root>/.rebuild_tmp`` and install in crash-safe order
(segments → slog → WAL → manifest last), so an interrupted rebuild
either restarts from scratch or boots from a WAL-only prefix that full
replay reconstructs.
"""

from __future__ import annotations

import logging
import os
import shutil
import time

from oceanbase_tpu.native import crc64
from oceanbase_tpu.server import admission as qadmission
from oceanbase_tpu.server import trace as qtrace
from oceanbase_tpu.storage.integrity import CorruptionError

log = logging.getLogger(__name__)

#: per-chunk crc-mismatch refetch budget (on top of the rpc-level retry
#: policy — that one covers LOST frames, this one corrupted payloads)
CHUNK_CRC_RETRIES = 3

#: default chunk budget per rebuild.fetch_segments call (overridable via
#: the rebuild_chunk_bytes knob); well under the 1 GiB frame cap
DEFAULT_CHUNK_BYTES = 4 << 20

#: generic name the wire uses for the peer's replica WAL file — each
#: side maps it to its own replica id's path
WAL_NAME = "wal/replica.log"


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


class RebuildServer:
    """The peer half: serves its own root dir as a rebuild baseline."""

    def __init__(self, node):
        self.node = node
        # whole-file digest cache for fetch_meta's listing: baseline
        # data files are write-once under a given name, so (size,
        # mtime_ns) identity makes re-reading the whole dataset per
        # fetch_meta call unnecessary — repairs call fetch_meta per
        # table/attempt and must not pay O(dataset) each time
        self._crc_cache: dict[str, tuple[int, int, int]] = {}

    def handlers(self) -> dict:
        return {"rebuild.fetch_meta": self.fetch_meta,
                "rebuild.fetch_segments": self.fetch_segments}

    def _wal_path(self) -> str:
        return os.path.join(self.node.root, "wal",
                            f"replica_{self.node.node_id}.log")

    def _data_dir(self) -> str:
        return os.path.join(self.node.root, "data")

    def fetch_meta(self):
        """Checkpoint, then describe the baseline a wiped peer needs.
        Checkpointing first bounds the WAL tail the rebuilt node must
        replay; the manifest + slog ship INLINE so they are atomic with
        the segment list (a later checkpoint racing the chunked segment
        downloads must not hand the client a newer manifest referencing
        segments it never listed — boot would silently skip them)."""
        self.node.tenant.checkpoint()
        ddir = self._data_dir()

        def read(path):
            try:
                with open(path, "rb") as f:
                    return f.read()
            except OSError:
                return b""

        manifest = read(os.path.join(ddir, "manifest.json"))
        slog = read(os.path.join(ddir, "slog.jsonl"))
        files = []
        for base, _dirs, names in os.walk(ddir):
            for n in sorted(names):
                if n.endswith(".tmp") or ".corrupt" in n or \
                        n in ("manifest.json", "slog.jsonl"):
                    continue
                p = os.path.join(base, n)
                rel = os.path.join("data", os.path.relpath(p, ddir))
                # immutable data files carry a whole-file digest the
                # client re-verifies after chunked assembly (the WAL is
                # append-only — its digest would race appends; its
                # entry-level crc64s cover it at boot instead)
                files.append({"name": rel, "size": os.path.getsize(p),
                              "kind": "data", "crc": self._file_crc(p)})
        wal = self._wal_path()
        if os.path.exists(wal):
            files.append({"name": WAL_NAME,
                          "size": os.path.getsize(wal), "kind": "wal"})
        return {"node_id": self.node.node_id,
                "wal_lsn": self.node.engine.meta.get("wal_lsn", 0),
                "role": self.node.palf.replica.role,
                "manifest": manifest, "slog": slog,
                "manifest_crc": crc64(manifest), "slog_crc": crc64(slog),
                "files": files}

    def _file_crc(self, path: str) -> int:
        """crc64 of one baseline file, cached by (size, mtime_ns)
        identity — sound because data files are write-once under a
        given name (compaction/repair mint fresh ids)."""
        st = os.stat(path)
        hit = self._crc_cache.get(path)
        if hit is not None and hit[0] == st.st_size \
                and hit[1] == st.st_mtime_ns:
            return hit[2]
        with open(path, "rb") as f:
            crc = crc64(f.read())
        self._crc_cache[path] = (st.st_size, st.st_mtime_ns, crc)
        return crc

    def _resolve(self, name: str) -> str:
        """Map a wire file name to a real path, refusing traversal.
        Normalize BEFORE the prefix check: 'data/../config.json' would
        otherwise pass both a raw startswith('data/') test and the
        root containment test while escaping the data dir."""
        if name == WAL_NAME:
            return self._wal_path()
        norm = os.path.normpath(str(name))
        if os.path.isabs(norm) or \
                not norm.startswith("data" + os.sep) or \
                ".." in norm.split(os.sep):
            raise PermissionError(f"rebuild: refusing path {name!r}")
        root = os.path.abspath(self.node.root)
        p = os.path.abspath(os.path.join(root, norm))
        if not p.startswith(root + os.sep):
            raise PermissionError(f"rebuild: refusing path {name!r}")
        return p

    def fetch_segments(self, name: str, offset: int = 0,
                       limit: int = DEFAULT_CHUNK_BYTES):
        limit = max(1, min(int(limit), DEFAULT_CHUNK_BYTES * 4))
        p = self._resolve(str(name))
        size = os.path.getsize(p)
        with open(p, "rb") as f:
            f.seek(int(offset))
            data = f.read(limit)
        return {"data": data, "size": size, "crc": crc64(data),
                "eof": int(offset) + len(data) >= size}


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


def needs_rebuild(root: str, node_id: int) -> bool:
    """A node needs a rebuild when it has NO local recovery sources: no
    manifest, no slog, and no (non-trivial) replica WAL.  A partially
    wiped node (WAL kept) boots by full replay instead."""
    data = os.path.join(root, "data")
    if os.path.exists(os.path.join(data, "manifest.json")):
        return False
    slog = os.path.join(data, "slog.jsonl")
    if os.path.exists(slog) and os.path.getsize(slog) > 0:
        return False
    wal = os.path.join(root, "wal", f"replica_{node_id}.log")
    # magic-only file == empty log
    return not (os.path.exists(wal) and os.path.getsize(wal) > 8)


def _pick_source(peers: dict) -> tuple[int, object, dict] | None:
    """Probe peers; prefer the leader, else the longest committed log.
    Returns (peer_id, client, state) or None when no peer has data."""
    from oceanbase_tpu.net.rpc import RpcError

    best = None
    for pid, cli in sorted(peers.items()):
        qadmission.checkpoint()  # KILL/deadline between peer probes
        try:
            st = cli.call("palf.state", _deadline_s=2.0)
        except (OSError, RpcError):
            # unreachable OR mid-boot/handler error: try the next peer
            continue
        committed = int(st.get("committed_lsn", 0))
        if committed <= 0:
            continue
        rank = (1 if st.get("role") == "leader" else 0, committed)
        if best is None or rank > best[0]:
            best = (rank, pid, cli, st)
    return None if best is None else best[1:]


def fetch_file(cli, name: str, dst: str,
               chunk_bytes: int = DEFAULT_CHUNK_BYTES,
               expect_crc: int | None = None) -> int:
    """Stream one baseline file over chunked ``rebuild.fetch_segments``
    with every chunk crc-verified before it is written (a corrupt chunk
    re-fetches, bounded by CHUNK_CRC_RETRIES) and an optional whole-file
    digest check after assembly.  -> bytes downloaded.  Shared by the
    wiped-node rebuild AND the scrub plane's segment repair."""
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    nbytes = 0
    with open(dst, "wb") as out:
        off = 0
        while True:
            qadmission.checkpoint()  # KILL/deadline between chunks
            r = None
            for attempt in range(CHUNK_CRC_RETRIES):
                r = cli.call("rebuild.fetch_segments", name=name,
                             offset=off, limit=int(chunk_bytes))
                if "crc" not in r or crc64(r["data"]) == r["crc"]:
                    break
                log.warning("rebuild: chunk crc mismatch %s@%d "
                            "(attempt %d)", name, off, attempt + 1)
            else:
                raise CorruptionError(
                    f"rebuild chunk crc mismatch after "
                    f"{CHUNK_CRC_RETRIES} attempts: {name}@{off}",
                    kind="rebuild", path=name)
            out.write(r["data"])
            off += len(r["data"])
            nbytes += len(r["data"])
            if r["eof"] or not r["data"]:
                break
    if expect_crc is not None:
        with open(dst, "rb") as f:
            got = crc64(f.read())
        if got != expect_crc:
            raise CorruptionError(
                f"rebuild file digest mismatch: {name}",
                kind="rebuild", path=name)
    return nbytes


def rebuild_from_peer(root: str, node_id: int, peers: dict,
                      recovery=None,
                      chunk_bytes: int = DEFAULT_CHUNK_BYTES):
    """Stream a peer's checkpoint + segments + WAL into ``root``.
    Returns a stats dict, or None when no peer has anything to offer
    (fresh-cluster boot)."""
    src = _pick_source(peers)
    if src is None:
        return None
    pid, cli, _st = src
    t0 = time.monotonic()
    with qtrace.span("rebuild.fetch", peer=pid) as sp:
        meta = cli.call("rebuild.fetch_meta")
        tmp = os.path.join(root, ".rebuild_tmp")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        nbytes = 0
        for f in meta["files"]:
            qadmission.checkpoint()  # KILL/deadline between files
            dst = os.path.join(tmp, f["name"])
            nbytes += fetch_file(cli, f["name"], dst,
                                 chunk_bytes=int(chunk_bytes),
                                 expect_crc=f.get("crc"))
        # manifest + slog came inline with fetch_meta: the point-in-time
        # pair that matches the segment list we just streamed — each
        # verified against its fetch_meta digest before install
        os.makedirs(os.path.join(tmp, "data"), exist_ok=True)
        for rel, blob, crc in (
                ("slog.jsonl", meta.get("slog", b""),
                 meta.get("slog_crc")),
                ("manifest.json", meta.get("manifest", b""),
                 meta.get("manifest_crc"))):
            if blob:
                if crc is not None and crc64(blob) != crc:
                    raise CorruptionError(
                        f"rebuild {rel} digest mismatch",
                        kind="rebuild", path=rel)
                with open(os.path.join(tmp, "data", rel), "wb") as out:
                    out.write(blob)
                nbytes += len(blob)
        _install(root, node_id, tmp, meta["files"])
        shutil.rmtree(tmp, ignore_errors=True)
        sp.tags.update(files=len(meta["files"]), bytes=nbytes)
    stats = {"peer": pid, "files": len(meta["files"]), "bytes": nbytes,
             "wal_lsn": int(meta.get("wal_lsn", 0)),
             "elapsed_s": time.monotonic() - t0}
    log.warning("node %d: rebuilt from peer %d — %d files, %d bytes, "
                "checkpoint replay point %d", node_id, pid,
                stats["files"], nbytes, stats["wal_lsn"])
    if recovery is not None:
        recovery.record("rebuild", peer=pid, nbytes=nbytes,
                        entries=len(meta["files"]),
                        wal_end_lsn=stats["wal_lsn"],
                        elapsed_s=stats["elapsed_s"],
                        note=f"files={stats['files']}")
    return stats


def _install(root: str, node_id: int, tmp: str, files: list[dict]):
    """Move the downloaded baseline into place, manifest LAST: an
    interrupted install leaves either nothing (rebuild restarts) or a
    WAL-only prefix (full replay reconstructs it)."""

    def move(rel_src: str, rel_dst: str):
        src = os.path.join(tmp, rel_src)
        if not os.path.exists(src):
            return
        dst = os.path.join(root, rel_dst)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(src, dst)

    manifest = os.path.join("data", "manifest.json")
    slog = os.path.join("data", "slog.jsonl")
    for f in files:
        if f["kind"] == "data" and f["name"] != manifest:
            move(f["name"], f["name"])
    move(slog, slog)
    move(WAL_NAME, os.path.join("wal", f"replica_{node_id}.log"))
    move(manifest, manifest)


def quarantine_corrupt_baseline(root: str, recovery=None):
    """Pre-boot integrity check of the local checkpoint baseline: a
    manifest or slog that fails its digest is quarantined (BOTH move
    aside — they are one point-in-time pair) so boot never trusts a
    rotten table/segment list.  The WAL stays: its entry-level crc64s
    self-verify at open, and full replay + leader catch-up reconstruct
    the state the quarantined checkpoint described."""
    from oceanbase_tpu.storage.engine import (
        load_manifest,
        quarantine_file,
        read_slog,
    )

    data = os.path.join(root, "data")
    manifest = os.path.join(data, "manifest.json")
    slog = os.path.join(data, "slog.jsonl")
    bad = None
    try:
        if os.path.exists(manifest):
            load_manifest(manifest)
        if os.path.exists(slog) and os.path.getsize(slog):
            for _op in read_slog(slog):
                pass
    except CorruptionError as e:
        bad = e
    if bad is None:
        return False
    quarantined = []
    for p in (manifest, slog):
        if os.path.exists(p):
            quarantined.append(os.path.basename(quarantine_file(p)))
    log.warning("node baseline corrupt (%s): quarantined %s; booting "
                "by WAL replay / rebuild", bad, quarantined)
    if recovery is not None:
        recovery.record("quarantine", note=f"{bad.kind or 'baseline'} "
                        f"digest mismatch -> {','.join(quarantined)}")
    return True


def maybe_rebuild(root: str, node_id: int, peers: dict, recovery=None,
                  chunk_bytes: int = DEFAULT_CHUNK_BYTES):
    """The boot hook: rebuild iff this node is wiped AND a peer has
    data.  Runs BEFORE the engine/WAL open, so a rebuilt node boots
    through the ordinary restart path (checkpoint + WAL tail replay).
    A baseline failing its digests counts as wiped-of-baseline: the
    corrupt manifest/slog quarantine first, then either the rebuild
    path (no WAL) or full WAL replay reconstructs state."""
    from oceanbase_tpu.net.rpc import RpcError

    if not root:
        return None
    quarantine_corrupt_baseline(root, recovery=recovery)
    if not needs_rebuild(root, node_id):
        return None
    try:
        return rebuild_from_peer(root, node_id, peers,
                                 recovery=recovery,
                                 chunk_bytes=chunk_bytes)
    except (OSError, RpcError, CorruptionError) as e:
        # a source dying mid-rebuild (or shipping bytes that fail their
        # digests past the retry budget) leaves only .rebuild_tmp
        # behind: boot continues empty and catch-up replays the log
        log.warning("node %d: rebuild aborted (%s); booting empty",
                    node_id, e)
        shutil.rmtree(os.path.join(root, ".rebuild_tmp"),
                      ignore_errors=True)
        return None
