"""Wiped-replica rebuild: bootstrap a node from a peer's checkpoint +
WAL, then let ordinary catch-up finish the job.

Reference analog: the replica rebuild / migration dag-nets
(src/storage/high_availability/ob_storage_ha_dag.h,
ob_ls_migration_handler) — a new or wiped replica copies a consistent
baseline (tablet metas + macro blocks ≙ manifest + segment files) from a
source replica, then replays the log tail.

Protocol (server side registered on every NodeServer):

    rebuild.fetch_meta()
        -> {"node_id", "wal_lsn", "role", "manifest": bytes,
            "slog": bytes, "files": [{"name", "size",
            "kind": "data" | "wal"}]}
        The peer checkpoints first and ships the manifest + slog BYTES
        inline (atomic with the file list — a checkpoint racing the
        chunked downloads cannot hand the client a NEWER manifest whose
        segments were never listed).  The listed segment files are
        immutable once written and never deleted; the WAL file is
        append-only — a chunk boundary racing an append at worst tears
        the final entry, which the torn-tail scan at boot truncates and
        catch-up re-ships.

    rebuild.fetch_segments(name, offset, limit)
        -> {"data": bytes, "eof": bool, "size": int}
        One chunk of one baseline file (byte-accounted, idempotent —
        the retry budget in net/rpc.py::POLICIES applies).

Client side (``maybe_rebuild``) runs BEFORE the tenant boots: files
download into ``<root>/.rebuild_tmp`` and install in crash-safe order
(segments → slog → WAL → manifest last), so an interrupted rebuild
either restarts from scratch or boots from a WAL-only prefix that full
replay reconstructs.
"""

from __future__ import annotations

import logging
import os
import shutil
import time

from oceanbase_tpu.server import trace as qtrace

log = logging.getLogger(__name__)

#: default chunk budget per rebuild.fetch_segments call (overridable via
#: the rebuild_chunk_bytes knob); well under the 1 GiB frame cap
DEFAULT_CHUNK_BYTES = 4 << 20

#: generic name the wire uses for the peer's replica WAL file — each
#: side maps it to its own replica id's path
WAL_NAME = "wal/replica.log"


# ---------------------------------------------------------------------------
# server side
# ---------------------------------------------------------------------------


class RebuildServer:
    """The peer half: serves its own root dir as a rebuild baseline."""

    def __init__(self, node):
        self.node = node

    def handlers(self) -> dict:
        return {"rebuild.fetch_meta": self.fetch_meta,
                "rebuild.fetch_segments": self.fetch_segments}

    def _wal_path(self) -> str:
        return os.path.join(self.node.root, "wal",
                            f"replica_{self.node.node_id}.log")

    def _data_dir(self) -> str:
        return os.path.join(self.node.root, "data")

    def fetch_meta(self):
        """Checkpoint, then describe the baseline a wiped peer needs.
        Checkpointing first bounds the WAL tail the rebuilt node must
        replay; the manifest + slog ship INLINE so they are atomic with
        the segment list (a later checkpoint racing the chunked segment
        downloads must not hand the client a newer manifest referencing
        segments it never listed — boot would silently skip them)."""
        self.node.tenant.checkpoint()
        ddir = self._data_dir()

        def read(path):
            try:
                with open(path, "rb") as f:
                    return f.read()
            except OSError:
                return b""

        manifest = read(os.path.join(ddir, "manifest.json"))
        slog = read(os.path.join(ddir, "slog.jsonl"))
        files = []
        for base, _dirs, names in os.walk(ddir):
            for n in sorted(names):
                if n.endswith(".tmp") or \
                        n in ("manifest.json", "slog.jsonl"):
                    continue
                p = os.path.join(base, n)
                rel = os.path.join("data", os.path.relpath(p, ddir))
                files.append({"name": rel, "size": os.path.getsize(p),
                              "kind": "data"})
        wal = self._wal_path()
        if os.path.exists(wal):
            files.append({"name": WAL_NAME,
                          "size": os.path.getsize(wal), "kind": "wal"})
        return {"node_id": self.node.node_id,
                "wal_lsn": self.node.engine.meta.get("wal_lsn", 0),
                "role": self.node.palf.replica.role,
                "manifest": manifest, "slog": slog,
                "files": files}

    def _resolve(self, name: str) -> str:
        """Map a wire file name to a real path, refusing traversal.
        Normalize BEFORE the prefix check: 'data/../config.json' would
        otherwise pass both a raw startswith('data/') test and the
        root containment test while escaping the data dir."""
        if name == WAL_NAME:
            return self._wal_path()
        norm = os.path.normpath(str(name))
        if os.path.isabs(norm) or \
                not norm.startswith("data" + os.sep) or \
                ".." in norm.split(os.sep):
            raise PermissionError(f"rebuild: refusing path {name!r}")
        root = os.path.abspath(self.node.root)
        p = os.path.abspath(os.path.join(root, norm))
        if not p.startswith(root + os.sep):
            raise PermissionError(f"rebuild: refusing path {name!r}")
        return p

    def fetch_segments(self, name: str, offset: int = 0,
                       limit: int = DEFAULT_CHUNK_BYTES):
        limit = max(1, min(int(limit), DEFAULT_CHUNK_BYTES * 4))
        p = self._resolve(str(name))
        size = os.path.getsize(p)
        with open(p, "rb") as f:
            f.seek(int(offset))
            data = f.read(limit)
        return {"data": data, "size": size,
                "eof": int(offset) + len(data) >= size}


# ---------------------------------------------------------------------------
# client side
# ---------------------------------------------------------------------------


def needs_rebuild(root: str, node_id: int) -> bool:
    """A node needs a rebuild when it has NO local recovery sources: no
    manifest, no slog, and no (non-trivial) replica WAL.  A partially
    wiped node (WAL kept) boots by full replay instead."""
    data = os.path.join(root, "data")
    if os.path.exists(os.path.join(data, "manifest.json")):
        return False
    slog = os.path.join(data, "slog.jsonl")
    if os.path.exists(slog) and os.path.getsize(slog) > 0:
        return False
    wal = os.path.join(root, "wal", f"replica_{node_id}.log")
    # magic-only file == empty log
    return not (os.path.exists(wal) and os.path.getsize(wal) > 8)


def _pick_source(peers: dict) -> tuple[int, object, dict] | None:
    """Probe peers; prefer the leader, else the longest committed log.
    Returns (peer_id, client, state) or None when no peer has data."""
    from oceanbase_tpu.net.rpc import RpcError

    best = None
    for pid, cli in sorted(peers.items()):
        try:
            st = cli.call("palf.state", _deadline_s=2.0)
        except (OSError, RpcError):
            # unreachable OR mid-boot/handler error: try the next peer
            continue
        committed = int(st.get("committed_lsn", 0))
        if committed <= 0:
            continue
        rank = (1 if st.get("role") == "leader" else 0, committed)
        if best is None or rank > best[0]:
            best = (rank, pid, cli, st)
    return None if best is None else best[1:]


def rebuild_from_peer(root: str, node_id: int, peers: dict,
                      recovery=None,
                      chunk_bytes: int = DEFAULT_CHUNK_BYTES):
    """Stream a peer's checkpoint + segments + WAL into ``root``.
    Returns a stats dict, or None when no peer has anything to offer
    (fresh-cluster boot)."""
    src = _pick_source(peers)
    if src is None:
        return None
    pid, cli, _st = src
    t0 = time.monotonic()
    with qtrace.span("rebuild.fetch", peer=pid) as sp:
        meta = cli.call("rebuild.fetch_meta")
        tmp = os.path.join(root, ".rebuild_tmp")
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp, exist_ok=True)
        nbytes = 0
        for f in meta["files"]:
            dst = os.path.join(tmp, f["name"])
            os.makedirs(os.path.dirname(dst), exist_ok=True)
            with open(dst, "wb") as out:
                off = 0
                while True:
                    r = cli.call("rebuild.fetch_segments",
                                 name=f["name"], offset=off,
                                 limit=int(chunk_bytes))
                    out.write(r["data"])
                    off += len(r["data"])
                    nbytes += len(r["data"])
                    if r["eof"] or not r["data"]:
                        break
        # manifest + slog came inline with fetch_meta: the point-in-time
        # pair that matches the segment list we just streamed
        os.makedirs(os.path.join(tmp, "data"), exist_ok=True)
        for rel, blob in (("slog.jsonl", meta.get("slog", b"")),
                          ("manifest.json", meta.get("manifest", b""))):
            if blob:
                with open(os.path.join(tmp, "data", rel), "wb") as out:
                    out.write(blob)
                nbytes += len(blob)
        _install(root, node_id, tmp, meta["files"])
        shutil.rmtree(tmp, ignore_errors=True)
        sp.tags.update(files=len(meta["files"]), bytes=nbytes)
    stats = {"peer": pid, "files": len(meta["files"]), "bytes": nbytes,
             "wal_lsn": int(meta.get("wal_lsn", 0)),
             "elapsed_s": time.monotonic() - t0}
    log.warning("node %d: rebuilt from peer %d — %d files, %d bytes, "
                "checkpoint replay point %d", node_id, pid,
                stats["files"], nbytes, stats["wal_lsn"])
    if recovery is not None:
        recovery.record("rebuild", peer=pid, nbytes=nbytes,
                        entries=len(meta["files"]),
                        wal_end_lsn=stats["wal_lsn"],
                        elapsed_s=stats["elapsed_s"],
                        note=f"files={stats['files']}")
    return stats


def _install(root: str, node_id: int, tmp: str, files: list[dict]):
    """Move the downloaded baseline into place, manifest LAST: an
    interrupted install leaves either nothing (rebuild restarts) or a
    WAL-only prefix (full replay reconstructs it)."""

    def move(rel_src: str, rel_dst: str):
        src = os.path.join(tmp, rel_src)
        if not os.path.exists(src):
            return
        dst = os.path.join(root, rel_dst)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        os.replace(src, dst)

    manifest = os.path.join("data", "manifest.json")
    slog = os.path.join("data", "slog.jsonl")
    for f in files:
        if f["kind"] == "data" and f["name"] != manifest:
            move(f["name"], f["name"])
    move(slog, slog)
    move(WAL_NAME, os.path.join("wal", f"replica_{node_id}.log"))
    move(manifest, manifest)


def maybe_rebuild(root: str, node_id: int, peers: dict, recovery=None,
                  chunk_bytes: int = DEFAULT_CHUNK_BYTES):
    """The boot hook: rebuild iff this node is wiped AND a peer has
    data.  Runs BEFORE the engine/WAL open, so a rebuilt node boots
    through the ordinary restart path (checkpoint + WAL tail replay)."""
    from oceanbase_tpu.net.rpc import RpcError

    if not root or not needs_rebuild(root, node_id):
        return None
    try:
        return rebuild_from_peer(root, node_id, peers,
                                 recovery=recovery,
                                 chunk_bytes=chunk_bytes)
    except (OSError, RpcError) as e:
        # a source dying mid-rebuild leaves only .rebuild_tmp behind:
        # boot continues empty and ordinary catch-up replays the log
        log.warning("node %d: rebuild aborted (%s); booting empty",
                    node_id, e)
        shutil.rmtree(os.path.join(root, ".rebuild_tmp"),
                      ignore_errors=True)
        return None
