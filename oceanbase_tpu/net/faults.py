"""Deterministic fault-injection plane for the host RPC layer.

Reference analog: the errsim tracepoint system scoped to the rpc frame
(deps/oblib/src/lib/utility/ob_tracepoint.h) plus the net error
simulation mittest uses to script nemesis schedules (packet loss, delay,
network partition) against a live cluster.  `server/errsim.py` already
covers *local* tracepoints; this plane covers the WIRE: every frame the
rpc client sends and every frame the server receives/replies consults
it, so tests and `scripts/chaos_bench.py` can inject message loss,
latency, partitions, frame corruption, and process crashes — seeded, so
a failing nemesis schedule replays exactly.

One `FaultPlane` instance per node process (NodeServer owns it and
shares it between its `RpcServer` and every peer `RpcClient`); the
`fault.inject` / `fault.clear` admin RPC verbs arm rules remotely.

Rule vocabulary (the actions the consult sites understand):

    drop    send: raise FaultDrop before the frame leaves (the caller
            KNOWS the handler never ran — retry-safe).
            recv: the server silently swallows the request (the caller
            cannot know; it rides its deadline — the lost-request case).
            reply: the handler RAN but the response is swallowed (the
            lost-reply case non-idempotent verbs must never resend).
    reset   like drop, but the connection closes instead of going
            silent — the fast-failure flavor of the same three cases.
    delay   sleep delay_ms before proceeding (slow network / GC pause).
    garble  flip bits in the frame payload (codec-level corruption; the
            receiver must close the desynchronized connection).
    crash   os._exit(137) — a process failure mid-protocol.
    bitflip/truncate (where="disk" only): corrupt a just-persisted file
            in place — ``verb`` names the artifact kind (segment,
            manifest, slog, wal, spill, backup).  The persistence
            boundaries (StorageEngine / PalfReplica) consult
            ``act_disk`` after every durable write, so seeded disk-rot
            schedules replay deterministically against the checksum +
            scrub plane.
    enospc/eio/partial (where="disk" only): write-ERROR injection,
            consulted via ``check_write`` BEFORE/INSIDE the durable
            writers (not after them like the rot rules).  enospc and
            eio raise ``OSError(errno.ENOSPC/EIO)`` with no bytes
            written; partial directs the writer to persist a seeded
            fraction of the batch and THEN fail with ENOSPC — the
            torn-write case the unwind paths (WAL truncate-back,
            tmp+rename) must clean up.  The boundaries normalize the
            OSError into typed DiskFull/DiskIOError
            (server/diskmgr.py).

Matching: verb (None = any), peer node id (None = any; on the client
side the destination, on the server side the sender's ``src`` field),
``where`` in {send, recv, reply}, then prob / nth / count gates.  Each
rule draws from its own `random.Random` seeded off the plane seed and
the rule id, so schedules are reproducible frame-for-frame.
"""

from __future__ import annotations

import errno as _errno
import itertools
import os
import random
import threading
import time
from dataclasses import dataclass, field

WHERES = ("send", "recv", "reply", "disk")
ACTIONS = ("drop", "reset", "delay", "garble", "crash",
           "bitflip", "truncate", "enospc", "eio", "partial")

#: post-write rot actions vs pre-write errno actions — both pair only
#: with where="disk" but consult at different boundaries (act_disk
#: after a durable write, check_write before/inside it), so each
#: consult site filters to its own family and the nth/count gates of
#: one family never tick on the other's matches
DISK_ROT_ACTIONS = ("bitflip", "truncate")
DISK_ERRNO_ACTIONS = ("enospc", "eio", "partial")

#: artifact kinds the persistence boundaries report to ``act_disk`` /
#: ``check_write`` (rule.verb matches against these; None = any)
DISK_KINDS = ("segment", "manifest", "slog", "wal", "spill", "backup",
              "workload")


class FaultDrop(ConnectionError):
    """A send-side injected drop: the frame never left the process."""


class FaultReset(ConnectionError):
    """An injected connection reset."""


@dataclass
class FaultRule:
    rule_id: int
    where: str
    action: str
    verb: str | None = None
    peer: int | None = None
    prob: float = 1.0
    nth: int | None = None        # fire on exactly the nth match (1-based)
    count: int = -1               # remaining fire budget (-1 = unlimited)
    delay_ms: float = 0.0
    matched: int = 0
    fired: int = 0
    rng: random.Random = field(default_factory=random.Random, repr=False)

    def to_dict(self) -> dict:
        return {"rule_id": self.rule_id, "where": self.where,
                "action": self.action, "verb": self.verb,
                "peer": self.peer, "prob": self.prob, "nth": self.nth,
                "count": self.count, "delay_ms": self.delay_ms,
                "matched": self.matched, "fired": self.fired}


class FaultPlane:
    """Seeded, process-local rule table consulted on every RPC frame."""

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._rules: list[FaultRule] = []
        self._ids = itertools.count(1)
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    # arming (the nemesis side)
    # ------------------------------------------------------------------
    def inject(self, where: str, action: str, verb: str | None = None,
               peer: int | None = None, prob: float = 1.0,
               nth: int | None = None, count: int = -1,
               delay_ms: float = 0.0, seed: int | None = None) -> int:
        """Install one rule; -> rule id (pass to ``clear``)."""
        if where not in WHERES:
            raise ValueError(f"where must be one of {WHERES}: {where!r}")
        if action not in ACTIONS:
            raise ValueError(
                f"action must be one of {ACTIONS}: {action!r}")
        if action == "garble" and where == "recv":
            # the server consults the plane only after decoding the
            # request, so recv-garble could never corrupt anything —
            # reject instead of silently arming a no-op; corrupt the
            # request with where="send" (client-side) instead
            raise ValueError(
                "garble is not applicable to where='recv'; use "
                "where='send' to corrupt requests")
        disk_only = DISK_ROT_ACTIONS + DISK_ERRNO_ACTIONS
        if (action in disk_only) != (where == "disk"):
            raise ValueError(
                f"{'/'.join(disk_only)} pair only with where='disk' "
                "(persisted-file faults; verb names the artifact kind)")
        if where == "disk" and verb is not None and \
                verb not in DISK_KINDS:
            raise ValueError(
                f"disk fault kind must be one of {DISK_KINDS}: {verb!r}")
        with self._lock:
            rid = next(self._ids)
            rule = FaultRule(
                rule_id=rid, where=where, action=action, verb=verb,
                peer=None if peer is None else int(peer),
                prob=float(prob),
                nth=None if nth is None else int(nth), count=int(count),
                delay_ms=float(delay_ms),
                rng=random.Random(self.seed * 1000003 + rid
                                  if seed is None else int(seed)))
            self._rules.append(rule)
            return rid

    # convenience spellings matching the nemesis vocabulary ------------
    def drop(self, verb: str | None = None, peer: int | None = None,
             prob: float = 1.0, nth: int | None = None,
             where: str = "send", count: int = -1) -> int:
        return self.inject(where, "drop", verb=verb, peer=peer,
                           prob=prob, nth=nth, count=count)

    def delay(self, ms: float, verb: str | None = None,
              peer: int | None = None, prob: float = 1.0,
              where: str = "send") -> int:
        return self.inject(where, "delay", verb=verb, peer=peer,
                           prob=prob, delay_ms=ms)

    def partition(self, peer: int) -> list[int]:
        """Cut all traffic with ``peer`` as seen from THIS node: frames
        to it never leave, frames from it are swallowed on receipt.
        (Install on both sides for a symmetric partition.)"""
        return [self.inject("send", "drop", peer=peer),
                self.inject("recv", "drop", peer=peer)]

    def crash_after(self, n_calls: int, verb: str | None = None,
                    where: str = "recv") -> int:
        """os._exit the process on the (n_calls+1)-th matching frame."""
        return self.inject(where, "crash", verb=verb,
                           nth=int(n_calls) + 1)

    def garble_frame(self, verb: str | None = None, prob: float = 1.0,
                     where: str = "reply", nth: int | None = None) -> int:
        return self.inject(where, "garble", verb=verb, prob=prob,
                           nth=nth)

    def disk(self, action: str, kind: str | None = None,
             nth: int | None = None, count: int = 1,
             prob: float = 1.0, seed: int | None = None) -> int:
        """Arm one persisted-file fault: ``action`` in
        {bitflip, truncate} (post-write rot) or {enospc, eio, partial}
        (pre-write errno), ``kind`` in DISK_KINDS (None = any).
        Defaults to a one-shot (count=1) — media rot, not a firehose."""
        return self.inject("disk", action, verb=kind, nth=nth,
                           count=count, prob=prob, seed=seed)

    def clear(self, rule_id: int | None = None) -> int:
        """Remove one rule (or all when ``rule_id`` is None);
        -> rules removed."""
        with self._lock:
            before = len(self._rules)
            if rule_id is None:
                self._rules.clear()
            else:
                self._rules = [r for r in self._rules
                               if r.rule_id != int(rule_id)]
            return before - len(self._rules)

    def rules(self) -> list[dict]:
        with self._lock:
            return [r.to_dict() for r in self._rules]

    # ------------------------------------------------------------------
    # the consult site (rpc hot path)
    # ------------------------------------------------------------------
    def act(self, where: str, verb: str | None,
            peer: int | None = None,
            payload: bytes | None = None) -> bytes | None:
        """Consult the plane for one frame.  Raises FaultDrop/FaultReset,
        sleeps, crashes, or returns the (possibly garbled) payload.
        The no-rules fast path is one attribute read."""
        if not self._rules:
            return payload
        delays = 0.0
        verdict: str | None = None
        with self._lock:
            for r in self._rules:
                if r.where != where:
                    continue
                if r.verb is not None and r.verb != verb:
                    continue
                if r.peer is not None and r.peer != peer:
                    continue
                r.matched += 1
                if r.nth is not None and r.matched != r.nth:
                    continue
                if r.count == 0:
                    continue
                if r.prob < 1.0 and r.rng.random() >= r.prob:
                    continue
                if r.count > 0:
                    r.count -= 1
                r.fired += 1
                if r.action == "delay":
                    delays += r.delay_ms / 1000.0
                elif verdict is None:
                    verdict = r.action
        if delays > 0.0:
            time.sleep(delays)
        if verdict == "crash":
            os._exit(137)
        if verdict == "drop":
            raise FaultDrop(f"fault: dropped {where} {verb!r}")
        if verdict == "reset":
            raise FaultReset(f"fault: reset {where} {verb!r}")
        if verdict == "garble" and payload is not None:
            return _garble(payload)
        return payload


    # ------------------------------------------------------------------
    # the disk consult site (persistence boundaries: StorageEngine
    # segment/slog/manifest writes, PalfReplica WAL appends)
    # ------------------------------------------------------------------
    def act_disk(self, kind: str, path: str):
        """Consult the plane after ``path`` (an artifact of ``kind``)
        was durably written.  Armed bitflip/truncate rules corrupt the
        just-persisted bytes in place — the deterministic stand-in for
        media rot that the checksum plane must catch on the next read.
        The no-rules fast path is one attribute read."""
        if not self._rules:
            return
        actions: list[tuple[str, random.Random]] = []
        with self._lock:
            for r in self._rules:
                if r.where != "disk" or r.action not in DISK_ROT_ACTIONS:
                    continue
                if r.verb is not None and r.verb != kind:
                    continue
                r.matched += 1
                if r.nth is not None and r.matched != r.nth:
                    continue
                if r.count == 0:
                    continue
                if r.prob < 1.0 and r.rng.random() >= r.prob:
                    continue
                if r.count > 0:
                    r.count -= 1
                r.fired += 1
                actions.append((r.action, r.rng))
        for action, rng in actions:
            if action == "bitflip":
                bitflip_file(path, rng=rng)
            elif action == "truncate":
                truncate_file(path, rng=rng)

    def check_write(self, kind: str, path: str | None = None,
                    nbytes: int | None = None) -> int | None:
        """Consult the plane BEFORE durably writing an artifact of
        ``kind`` (the errno half of the disk plane; the rot half is
        ``act_disk`` after the write).

        - an armed ``enospc``/``eio`` rule raises
          ``OSError(errno.ENOSPC/EIO)`` — no bytes were written;
        - an armed ``partial`` rule returns how many of the batch's
          ``nbytes`` the writer must persist before failing with
          ENOSPC (a seeded fraction in (0, 1) of the batch) — the
          torn-write case; writers that cannot do partial writes (or
          pass no ``nbytes``) get a plain ENOSPC raise instead;
        - no matching rule -> None (proceed).

        The no-rules fast path is one attribute read."""
        if not self._rules:
            return None
        verdict: tuple[str, random.Random] | None = None
        with self._lock:
            for r in self._rules:
                if r.where != "disk" or \
                        r.action not in DISK_ERRNO_ACTIONS:
                    continue
                if r.verb is not None and r.verb != kind:
                    continue
                r.matched += 1
                if r.nth is not None and r.matched != r.nth:
                    continue
                if r.count == 0:
                    continue
                if r.prob < 1.0 and r.rng.random() >= r.prob:
                    continue
                if r.count > 0:
                    r.count -= 1
                r.fired += 1
                if verdict is None:
                    verdict = (r.action, r.rng)
        if verdict is None:
            return None
        action, rng = verdict
        if action == "eio":
            raise OSError(_errno.EIO,
                          f"fault: injected EIO on {kind} write", path)
        if action == "partial" and nbytes is not None and nbytes > 1:
            return max(1, min(nbytes - 1,
                              int(nbytes * rng.uniform(0.1, 0.9))))
        raise OSError(_errno.ENOSPC,
                      f"fault: injected ENOSPC on {kind} write", path)


def bitflip_file(path: str, rng: random.Random | None = None,
                 seed: int = 0) -> int:
    """Flip ONE seeded bit of ``path`` in place; -> the byte offset.
    Offsets draw from the middle 80% of the file so the flip lands in
    payload, not in the first magic bytes (whose corruption is a
    different, already-covered failure mode)."""
    rng = rng if rng is not None else random.Random(seed)
    size = os.path.getsize(path)
    if size == 0:
        return -1
    lo, hi = size // 10, max(size // 10 + 1, size - size // 10)
    off = rng.randrange(lo, hi)
    bit = 1 << rng.randrange(8)
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        f.seek(off)
        f.write(bytes([b[0] ^ bit]))
    return off


def truncate_file(path: str, rng: random.Random | None = None,
                  seed: int = 0) -> int:
    """Cut a seeded fraction (5–50%) off the file's tail; -> new size."""
    rng = rng if rng is not None else random.Random(seed)
    size = os.path.getsize(path)
    if size == 0:
        return 0
    keep = max(1, size - max(1, int(size * rng.uniform(0.05, 0.5))))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def _garble(payload: bytes) -> bytes:
    """Deterministically corrupt a frame body: invert a byte span in the
    middle (keeps length, so the length-prefixed framing stays intact —
    the DECODER must notice, exactly like single-bit wire corruption)."""
    if not payload:
        return payload
    b = bytearray(payload)
    lo = len(b) // 3
    hi = min(len(b), lo + 16) or 1
    for i in range(lo, hi):
        b[i] ^= 0xFF
    return bytes(b)
