"""Binary message codec: JSON control header + raw column buffers.

Reference analog: the obrpc serialization layer (OB_UNIS codegen,
deps/oblib/src/lib/utility/ob_unify_serialize.h) — here a message is a
Python dict whose numpy arrays are lifted out of the JSON body and sent
as length-prefixed binary sections, so snapshot scans ship column data
without base64/pickle overhead (pickle is also a non-starter across
trust boundaries).

Wire layout:
    u32 header_len | header json | u32 len0 | buf0 | u32 len1 | buf1 ...
header = {"body": <json with arrays replaced by {"__buf__": i}>,
          "bufs": [{"dtype": "<i8"} | {"dtype": "object", "len": n}
                   | {"dtype": "bytes"}]}
object/str arrays are encoded as UTF-8 with u32 length prefixes per
element (SQL strings), marked dtype "object".
"""

from __future__ import annotations

import json
import struct

import numpy as np

_U32 = struct.Struct("<I")
_NONE = 0xFFFFFFFF


def _encode_obj_array(a: np.ndarray) -> bytes:
    parts = []
    for v in a.tolist():
        if v is None:
            parts.append(_U32.pack(_NONE))
        else:
            b = str(v).encode("utf-8")
            parts.append(_U32.pack(len(b)) + b)
    return b"".join(parts)


def _decode_obj_array(buf: bytes, n: int) -> np.ndarray:
    out = np.empty(n, dtype=object)
    off = 0
    for i in range(n):
        (ln,) = _U32.unpack_from(buf, off)
        off += 4
        if ln == _NONE:
            out[i] = None
        else:
            out[i] = buf[off:off + ln].decode("utf-8")
            off += ln
    return out


def encode_msg(body) -> bytes:
    bufs: list[bytes] = []
    metas: list[dict] = []

    def lift(v):
        if isinstance(v, np.ndarray):
            if v.dtype == object or v.dtype.kind in "US":
                arr = v if v.dtype == object else v.astype(object)
                metas.append({"dtype": "object", "len": len(arr)})
                bufs.append(_encode_obj_array(arr))
            else:
                c = np.ascontiguousarray(v)
                metas.append({"dtype": c.dtype.str,
                              "shape": list(c.shape)})
                bufs.append(c.tobytes())
            return {"__buf__": len(bufs) - 1}
        if isinstance(v, (bytes, bytearray)):
            metas.append({"dtype": "bytes"})
            bufs.append(bytes(v))
            return {"__buf__": len(bufs) - 1}
        if isinstance(v, dict):
            if "__buf__" in v or "__esc__" in v:
                # escape user dicts that collide with the buffer sentinel
                return {"__esc__": {k: lift(x) for k, x in v.items()}}
            return {k: lift(x) for k, x in v.items()}
        if isinstance(v, (list, tuple)):
            return [lift(x) for x in v]
        if isinstance(v, (np.integer,)):
            return int(v)
        if isinstance(v, (np.floating,)):
            return float(v)
        if isinstance(v, np.bool_):
            return bool(v)
        return v

    header = json.dumps({"body": lift(body),
                         "bufs": metas}).encode("utf-8")
    out = [_U32.pack(len(header)), header]
    for b in bufs:
        out.append(_U32.pack(len(b)))
        out.append(b)
    return b"".join(out)


def decode_msg(data: bytes):
    (hlen,) = _U32.unpack_from(data, 0)
    header = json.loads(data[4:4 + hlen].decode("utf-8"))
    metas = header["bufs"]
    raw: list[bytes] = []
    off = 4 + hlen
    for _m in metas:
        (n,) = _U32.unpack_from(data, off)
        off += 4
        raw.append(data[off:off + n])
        off += n

    def sink(v):
        if isinstance(v, dict):
            if "__esc__" in v and len(v) == 1:
                return {k: sink(x) for k, x in v["__esc__"].items()}
            if "__buf__" in v and len(v) == 1:
                i = v["__buf__"]
                m = metas[i]
                if m["dtype"] == "bytes":
                    return raw[i]
                if m["dtype"] == "object":
                    return _decode_obj_array(raw[i], m["len"])
                a = np.frombuffer(raw[i], dtype=np.dtype(m["dtype"]))
                return a.reshape(m["shape"]).copy()
            return {k: sink(x) for k, x in v.items()}
        if isinstance(v, list):
            return [sink(x) for x in v]
        return v

    return sink(header["body"])
