"""Length-prefixed TCP RPC: threaded server + pooled client.

Reference analog: the rpc frame (deps/oblib/src/rpc/frame,
ObReqTransport + macro-generated ObRpcProxy stubs).  Here: one TCP
connection per client, u32-framed codec messages, a method-name
dispatch table on the server, synchronous request/response.

Request body:  {"method": str, "params": {...}, "rid": int}
Response body: {"rid": int, "ok": bool, "result": ... | "error": str}
"""

from __future__ import annotations

import itertools
import socket
import socketserver
import struct
import threading

from oceanbase_tpu.net.codec import decode_msg, encode_msg

_U32 = struct.Struct("<I")
MAX_MSG = 1 << 30


class RpcError(RuntimeError):
    """Remote handler raised; .kind carries the remote exception type."""

    def __init__(self, kind: str, msg: str):
        super().__init__(f"{kind}: {msg}")
        self.kind = kind


def _recv_exact(sock: socket.socket, n: int) -> bytes | None:
    chunks = []
    while n > 0:
        b = sock.recv(min(n, 1 << 20))
        if not b:
            return None
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _send_frame(sock: socket.socket, payload: bytes):
    sock.sendall(_U32.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket) -> bytes | None:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _U32.unpack(hdr)
    if n > MAX_MSG:
        raise RpcError("Protocol", f"frame too large: {n}")
    return _recv_exact(sock, n)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = _recv_frame(self.request)
            except (ConnectionError, OSError):
                return
            if frame is None:
                return
            msg = decode_msg(frame)
            rid = msg.get("rid", 0)
            fn = self.server.handlers.get(msg.get("method"))
            if fn is None:
                resp = {"rid": rid, "ok": False,
                        "error_kind": "NoSuchMethod",
                        "error": str(msg.get("method"))}
            else:
                try:
                    result = fn(**(msg.get("params") or {}))
                    resp = {"rid": rid, "ok": True, "result": result}
                except Exception as e:  # noqa: BLE001 — ship to caller
                    resp = {"rid": rid, "ok": False,
                            "error_kind": type(e).__name__,
                            "error": str(e)}
            try:
                _send_frame(self.request, encode_msg(resp))
            except (ConnectionError, OSError):
                return


class RpcServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str, port: int, handlers: dict):
        super().__init__((host, port), _Handler)
        self.handlers = dict(handlers)
        self._thread: threading.Thread | None = None

    def register(self, name: str, fn):
        self.handlers[name] = fn

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self):
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self.shutdown()
        self.server_close()


class RpcClient:
    """One connection, lazily (re)established; thread-safe via a lock
    (requests serialize per connection — fine for the host control
    plane; PX data stays on ICI collectives)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.addr = (host, port)
        self.timeout_s = timeout_s
        self._sock: socket.socket | None = None
        self._rid = itertools.count(1)
        self._lock = threading.Lock()

    def _connect(self):
        s = socket.create_connection(self.addr, timeout=self.timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = s

    def call(self, method: str, **params):
        return self.call_with_size(method, **params)[0]

    def call_with_size(self, method: str, **params):
        """Like call(), but also returns the wire cost:
        -> (result, sent_bytes, recv_bytes)."""
        with self._lock:
            req = encode_msg({"method": method, "params": params,
                              "rid": next(self._rid)})
            for attempt in (0, 1):
                if self._sock is None:
                    self._connect()
                try:
                    _send_frame(self._sock, req)
                except (ConnectionError, OSError):
                    # send failed -> the handler cannot have run; a stale
                    # pooled connection is the common cause, reconnect once
                    self.close()
                    if attempt:
                        raise
                    continue
                try:
                    frame = _recv_frame(self._sock)
                except (ConnectionError, OSError):
                    # the request MAY have executed remotely — never
                    # resend non-idempotent work; surface the failure
                    self.close()
                    raise
                break
            if frame is None:
                self.close()
                raise ConnectionError(f"peer {self.addr} closed")
            sent = len(req) + 4
            recv = len(frame) + 4
            resp = decode_msg(frame)
            if not resp.get("ok"):
                raise RpcError(resp.get("error_kind", "Remote"),
                               resp.get("error", ""))
            return resp.get("result"), sent, recv

    def ping(self) -> bool:
        try:
            return self.call("ping") == "pong"
        except (OSError, RpcError):
            return False

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
