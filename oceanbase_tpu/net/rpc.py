"""Length-prefixed TCP RPC: threaded server + pooled client.

Reference analog: the rpc frame (deps/oblib/src/rpc/frame,
ObReqTransport + macro-generated ObRpcProxy stubs).  Here: a small
per-client connection pool, u32-framed codec messages, a method-name
dispatch table on the server, synchronous request/response.

Request body:  {"method": str, "params": {...}, "rid": int, "src": int?}
Response body: {"rid": int, "ok": bool, "result": ... | "error": str}

Robustness plane (≙ ObRpcProxy timeout/retry discipline + the
ObReqTransport error path):

- every verb carries a **policy** (`POLICIES`): a deadline, an
  idempotence bit, and a retry budget.  Idempotent verbs (reads, state
  probes, the prev-lsn/term-checked PALF protocol) get jittered
  exponential backoff inside the deadline; non-idempotent verbs are
  NEVER resent once the request hit the wire — they fail fast at the
  deadline instead of riding a socket timeout.
- calls check out a pooled connection for the round-trip, so a slow bulk
  transfer cannot queue control-plane pings behind it.
- any mid-frame failure (including oversized/garbled frames) closes the
  connection instead of leaving unread bytes to desynchronize the next
  call.
- a `FaultPlane` (net/faults.py), when installed, is consulted on every
  frame in and out — the deterministic chaos hook.
"""

from __future__ import annotations

import itertools
import random
import select
import socket
import socketserver
import struct
import threading
import time
from dataclasses import dataclass

from oceanbase_tpu.net.codec import decode_msg, encode_msg
from oceanbase_tpu.net.faults import FaultDrop, FaultReset
from oceanbase_tpu.server import metrics as qmetrics
from oceanbase_tpu.server import trace as qtrace

_U32 = struct.Struct("<I")
MAX_MSG = 1 << 30

# per-verb wire accounting (host side, recorded at the call/reply
# boundary — the cluster half of gv$sysstat; scripts/metrics_bench.py
# reconciles rpc.bytes against gv$px_exchange)
qmetrics.declare("rpc.calls", "counter",
                 "client calls that returned a decoded reply", )
qmetrics.declare("rpc.failures", "counter",
                 "client calls that terminally failed")
qmetrics.declare("rpc.bytes", "counter",
                 "wire bytes (request+reply frames) of successful calls")
qmetrics.declare("rpc.retries", "counter",
                 "resend attempts (idempotent verbs only)")
qmetrics.declare("rpc.deadline_exceeded", "counter",
                 "calls that died at the verb policy's deadline")
qmetrics.declare("rpc.call_s", "histogram",
                 "per-attempt round-trip latency of successful calls",
                 unit="s")
qmetrics.declare("rpc.served", "counter",
                 "server-side handler invocations")


class RpcError(RuntimeError):
    """Remote handler raised; .kind carries the remote exception type."""

    def __init__(self, kind: str, msg: str):
        super().__init__(f"{kind}: {msg}")
        self.kind = kind


class ProtocolError(RpcError):
    """Frame-level corruption (oversized header, undecodable body).
    The connection is desynchronized and must be closed."""

    def __init__(self, msg: str):
        super().__init__("Protocol", msg)


class DeadlineExceeded(TimeoutError):
    """The verb's deadline elapsed before a reply arrived.  Subclasses
    TimeoutError (hence OSError) so every existing ``except OSError``
    failure path treats it as the network fault it is."""


class ConnPoolExhausted(DeadlineExceeded):
    """Checkout hit the per-peer connection cap (rpc_max_conns_per_peer)
    and no socket freed inside the call's remaining deadline — the
    typed fail-fast for fan-out overload, instead of dialing without
    bound."""


# ---------------------------------------------------------------------------
# per-verb deadline / retry policy table (≙ the proxy stubs' timeout +
# OB_RPC_NEED_RETRY discipline, declared per verb instead of per call site)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class VerbPolicy:
    deadline_s: float          # end-to-end budget for the call
    idempotent: bool           # may the request be RESENT after it was sent?
    max_retries: int = 0       # resend budget (idempotent only)
    backoff_base_s: float = 0.05
    backoff_cap_s: float = 1.0


#: Verbs absent from this table get DEFAULT_POLICY: non-idempotent,
#: never resent, 10 s deadline.  Idempotence notes:
#: - reads / state probes are trivially idempotent;
#: - palf.vote: the acceptor grants at most one vote per term and
#:   re-answers the same candidate identically — re-ask is safe;
#: - palf.accept/commit: prev-lsn/term-checked appends and commit-point
#:   advances are idempotent (re-applying is a no-op), the Raft property;
#: - sql.execute carries DML — never resent, the session retries at the
#:   statement layer where NotLeader routing decides.
POLICIES: dict[str, VerbPolicy] = {
    "ping":         VerbPolicy(1.0, True, 2, 0.02, 0.10),
    "node.state":   VerbPolicy(2.0, True, 2, 0.02, 0.20),
    "palf.state":   VerbPolicy(2.0, True, 2, 0.02, 0.20),
    "palf.vote":    VerbPolicy(2.0, True, 1, 0.02, 0.20),
    "palf.accept":  VerbPolicy(10.0, True, 1, 0.05, 0.50),
    "palf.commit":  VerbPolicy(5.0, True, 1, 0.02, 0.20),
    "das.scan":     VerbPolicy(30.0, True, 3, 0.05, 1.00),
    "das.pull":     VerbPolicy(120.0, True, 2, 0.05, 1.00),
    "dtl.execute":  VerbPolicy(120.0, True, 2, 0.10, 2.00),
    # fault.inject MUTATES plane state and mints a fresh rule id per
    # call — a lost-reply resend would double-arm the rule, so it is
    # non-idempotent; clear (remove by id / remove all) re-applies
    # harmlessly
    "fault.inject": VerbPolicy(5.0, False),
    "fault.clear":  VerbPolicy(5.0, True, 2, 0.02, 0.20),
    "cluster.health": VerbPolicy(2.0, True, 2, 0.02, 0.20),
    "recovery.state": VerbPolicy(2.0, True, 2, 0.02, 0.20),
    # rebuild plane (net/rebuild.py): fetch_meta re-checkpoints on
    # resend (harmless — checkpoints are idempotent w.r.t. state) and
    # fetch_segments is a pure ranged read; both carry a retry budget
    # so a wiped node's bootstrap survives transient drops
    "rebuild.fetch_meta":     VerbPolicy(120.0, True, 2, 0.10, 1.00),
    "rebuild.fetch_segments": VerbPolicy(60.0, True, 3, 0.05, 1.00),
    # metrics.scrape is a pure read of monotonic counters — re-asking
    # returns a superset-or-equal snapshot, trivially idempotent
    "metrics.scrape": VerbPolicy(5.0, True, 2, 0.02, 0.20),
    # scrub plane (storage/scrub.py): checksum is a pure snapshot read;
    # run triggers a verify/repair round that CONVERGES — re-running
    # after a lost reply re-verifies already-repaired state, a no-op —
    # so both carry bounded retry budgets
    "scrub.checksum": VerbPolicy(60.0, True, 2, 0.05, 0.50),
    "scrub.run":      VerbPolicy(300.0, True, 1, 0.10, 1.00),
    # disk.takeover asks a peer with log-disk headroom to campaign:
    # elections are idempotent (a re-ask of the winner is a no-op, of a
    # loser another bounded campaign), so a lost reply may retry once
    "disk.takeover":  VerbPolicy(10.0, True, 1, 0.05, 0.50),
    # config.set writes one knob on the SERVING node (≙ ALTER SYSTEM
    # SET ... SERVER=...): re-setting the same value is a no-op, so a
    # lost reply may retry once; the deadline is generous because a
    # disk-budget change force-polls the disk manager, which can run a
    # full reclaim round (checkpoint + WAL recycle) synchronously
    "config.set":     VerbPolicy(30.0, True, 1, 0.05, 0.50),
    # dtl.cancel sets a cancel flag keyed by statement token — setting
    # an already-set flag is a no-op, trivially idempotent; it must
    # fail FAST (the canceller is usually unwinding a kill/timeout)
    "dtl.cancel":   VerbPolicy(2.0, True, 2, 0.02, 0.20),
    # workload.snapshot is a pure read of the node's diagnostic
    # surfaces (monotonic counters + point-in-time state) — re-asking
    # returns a superset-or-equal payload, trivially idempotent like
    # metrics.scrape; the deadline is wider because the payload spans
    # every surface, not one registry
    "workload.snapshot": VerbPolicy(10.0, True, 2, 0.05, 0.50),
    "sql.execute":  VerbPolicy(600.0, False),
}

DEFAULT_POLICY = VerbPolicy(10.0, False)


def verb_policy(method: str) -> VerbPolicy:
    return POLICIES.get(method, DEFAULT_POLICY)


def _recv_exact(sock: socket.socket, n: int,
                deadline: float | None = None) -> bytes | None:
    """``deadline`` (monotonic) makes the read END-TO-END bounded: the
    socket timeout is re-armed with the REMAINING budget before every
    chunk, so a peer trickling bytes cannot keep the call alive by
    resetting a fixed per-recv window each burst."""
    chunks = []
    while n > 0:
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise socket.timeout("deadline exceeded mid-frame")
            sock.settimeout(remaining)
        b = sock.recv(min(n, 1 << 20))
        if not b:
            return None
        chunks.append(b)
        n -= len(b)
    return b"".join(chunks)


def _send_frame(sock: socket.socket, payload: bytes):
    sock.sendall(_U32.pack(len(payload)) + payload)


def _recv_frame(sock: socket.socket,
                deadline: float | None = None) -> bytes | None:
    hdr = _recv_exact(sock, 4, deadline)
    if hdr is None:
        return None
    (n,) = _U32.unpack(hdr)
    if n > MAX_MSG:
        # unread bytes follow a bogus header — the stream is
        # desynchronized; both consult sites close the connection on
        # ProtocolError so the next call starts on a clean socket
        raise ProtocolError(f"frame too large: {n}")
    return _recv_exact(sock, n, deadline)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        self.request.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        while True:
            try:
                frame = _recv_frame(self.request)
            except ProtocolError:
                return  # desynchronized stream: drop the connection
            except (ConnectionError, OSError):
                return
            if frame is None:
                return
            try:
                msg = decode_msg(frame)
            except Exception:  # noqa: BLE001 — any codec failure
                return  # garbled frame: close, the client reconnects
            rid = msg.get("rid", 0)
            verb = msg.get("method")
            src = msg.get("src")
            faults = self.server.faults
            if faults is not None:
                try:
                    faults.act("recv", verb, src)
                except FaultDrop:
                    continue  # request lost in the network: no reply
                except FaultReset:
                    return
            fn = self.server.handlers.get(verb)
            # full-link trace continuation: a request carrying a trace
            # context runs its handler under a local TraceCtx parented
            # to the caller's rpc span; the spans ship back with the
            # reply (success AND error — a failed handler's timing is
            # exactly what the coordinator wants to attribute)
            tr = msg.get("trace")
            tctx = None
            tsid = 0
            if tr is not None and fn is not None:
                try:
                    tctx = qtrace.TraceCtx(str(tr["tid"]),
                                           node=self.server.node_id)
                    tsid = int(tr.get("sid", 0))
                except (KeyError, TypeError, ValueError):
                    tctx = None  # malformed context degrades tracing,
                    #              never the request itself
            if fn is None:
                resp = {"rid": rid, "ok": False,
                        "error_kind": "NoSuchMethod",
                        "error": str(verb)}
            else:
                try:
                    with qtrace.activate(tctx, tsid):
                        with qtrace.span(str(verb), src=src):
                            result = fn(**(msg.get("params") or {}))
                    resp = {"rid": rid, "ok": True, "result": result}
                    qmetrics.inc("rpc.served", verb=str(verb), ok=1)
                except Exception as e:  # noqa: BLE001 — ship to caller
                    # a handler that FORWARDED (sql.execute routing)
                    # re-raises an RpcError: preserve the original
                    # remote kind across the extra hop instead of
                    # collapsing every typed error to "RpcError"
                    kind = e.kind if isinstance(e, RpcError) \
                        else type(e).__name__
                    resp = {"rid": rid, "ok": False,
                            "error_kind": kind,
                            "error": str(e)}
                    qmetrics.inc("rpc.served", verb=str(verb), ok=0)
                if tctx is not None and tctx.spans:
                    resp["spans"] = [s.to_wire()
                                     for s in tctx.snapshot()]
            payload = encode_msg(resp)
            if faults is not None:
                # the handler RAN by now — a reply fault is the
                # lost-response case non-idempotent verbs must surface
                try:
                    payload = faults.act("reply", verb, src, payload)
                except FaultDrop:
                    continue
                except FaultReset:
                    return
            try:
                _send_frame(self.request, payload)
            except (ConnectionError, OSError):
                return


class RpcServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, host: str, port: int, handlers: dict,
                 faults=None, node_id: int = 0):
        super().__init__((host, port), _Handler)
        self.handlers = dict(handlers)
        self.faults = faults
        self.node_id = node_id  # stamps remote trace spans
        self._thread: threading.Thread | None = None

    def register(self, name: str, fn):
        self.handlers[name] = fn

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self):
        self._thread = threading.Thread(target=self.serve_forever,
                                        daemon=True)
        self._thread.start()

    def stop(self):
        self.shutdown()
        self.server_close()


class RpcClient:
    """Pooled connections to one peer, checkout/checkin per call.

    Each call owns a connection for exactly its round-trip, so a slow or
    hung bulk transfer (``dtl.execute`` on a cold jit cache) cannot queue
    control-plane pings or PALF heartbeats behind it.  Failed
    connections are closed, never returned to the pool.

    ``observer`` (optional) receives per-call outcomes — the failure
    detector's signal source: record_success(rtt_s) / record_failure() /
    record_retry() / record_deadline().
    """

    def __init__(self, host: str, port: int, timeout_s: float = 10.0,
                 peer_id: int | None = None, local_id: int | None = None,
                 faults=None, observer=None, pool_size: int = 4,
                 max_conns: int = 16):
        self.addr = (host, port)
        self.timeout_s = timeout_s  # connect timeout + policy fallback
        self.peer_id = peer_id
        self.local_id = local_id
        self.faults = faults
        self.observer = observer
        self._pool: list[socket.socket] = []   # idle; MRU at the end
        self._pool_size = pool_size            # idle cap (LRU closes)
        self._max_conns = max(max_conns, 1)    # live cap (idle+in-use)
        self._conns = 0                        # live sockets accounted
        self._rid = itertools.count(1)
        # guards pool list + live-socket count; waiters park on it when
        # checkout hits the live cap
        self._lock = threading.Condition()

    # -- pool ----------------------------------------------------------
    def _discard(self, s: socket.socket):
        """Close a socket this client accounted for (failure paths, LRU
        eviction) and wake a capped-out checkout waiter."""
        try:
            s.close()
        except OSError:
            pass
        with self._lock:
            self._conns = max(self._conns - 1, 0)
            self._lock.notify()

    def _checkout(self, timeout: float) -> socket.socket:
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                s = self._pool.pop() if self._pool else None
                if s is None:
                    if self._conns < self._max_conns:
                        # reserve the live-cap seat before the (slow,
                        # unlocked) dial; released on dial failure
                        self._conns += 1
                        break
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise ConnPoolExhausted(
                            f"{self.addr}: {self._max_conns} "
                            f"connections busy, none freed inside "
                            f"{timeout:.3f}s")
                    self._lock.wait(timeout=min(remaining, 0.05))
                    continue
            # an idle request/response socket should never be readable;
            # readable means the peer closed it (or sent garbage) while
            # pooled — discard instead of letting a doomed send turn
            # into a spurious "may have executed" on non-idempotent work
            r, _, _ = select.select([s], [], [], 0)
            if not r:
                s.settimeout(timeout)
                return s
            self._discard(s)
        try:
            s = socket.create_connection(
                self.addr, timeout=min(timeout, self.timeout_s))
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except BaseException:
            with self._lock:
                self._conns = max(self._conns - 1, 0)
                self._lock.notify()
            raise
        s.settimeout(timeout)
        return s

    def _checkin(self, s: socket.socket):
        extras: list[socket.socket] = []
        with self._lock:
            self._pool.append(s)
            # idle cap: close the LEAST-recently-used extras (index 0),
            # keeping the warm end of the pool
            while len(self._pool) > max(self._pool_size, 0):
                extras.append(self._pool.pop(0))
                self._conns = max(self._conns - 1, 0)
            self._lock.notify()
        for e in extras:
            try:
                e.close()
            except OSError:
                pass

    # -- calls ---------------------------------------------------------
    def call(self, method: str, _deadline_s: float | None = None,
             **params):
        return self.call_with_size(method, _deadline_s=_deadline_s,
                                   **params)[0]

    def call_with_size(self, method: str,
                       _deadline_s: float | None = None, **params):
        """Like call(), but also returns the wire cost:
        -> (result, sent_bytes, recv_bytes).

        ``_deadline_s`` overrides the verb policy's deadline (the
        heartbeat loop probes with a budget tied to its own period)."""
        pol = verb_policy(method)
        deadline_s = pol.deadline_s if _deadline_s is None \
            else float(_deadline_s)
        t0 = time.monotonic()
        deadline = t0 + deadline_s
        body = {"method": method, "params": params,
                "rid": next(self._rid)}
        if self.local_id is not None:
            body["src"] = self.local_id
        # full-link tracing: one client span covers the whole call
        # (retries included — the backoff IS the latency being traced);
        # the context rides the frame so the peer continues the tree
        tctx = qtrace.current()
        tspan = None
        if tctx is not None:
            tspan = qtrace.begin_span(
                tctx, "rpc." + str(method), qtrace.current_span_id(),
                peer=self.peer_id if self.peer_id is not None else -1)
            body["trace"] = {"tid": tctx.trace_id, "sid": tspan.span_id}
        req = encode_msg(body)
        obs = self.observer
        try:
            return self._call_loop(method, req, pol, deadline,
                                   deadline_s, obs, tctx, tspan)
        except BaseException as e:
            if tspan is not None:
                tspan.tags["error"] = type(e).__name__
            raise
        finally:
            if tspan is not None:
                qtrace.end_span(tctx, tspan)

    def _call_loop(self, method, req, pol, deadline, deadline_s,
                   obs, tctx, tspan):
        attempt = 0
        while True:
            sent_ok = False
            conn: socket.socket | None = None
            a0 = time.monotonic()  # per-ATTEMPT rtt (a success after
            #                        retries must not fold the failed
            #                        attempts' backoff into the ewma)
            try:
                payload = req
                if self.faults is not None:
                    # consult BEFORE computing the remaining budget: an
                    # injected delay must burn the deadline like real
                    # network latency would
                    payload = self.faults.act(
                        "send", method, self.peer_id, payload) or req
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise DeadlineExceeded(
                        f"{method} to {self.addr}: deadline "
                        f"{deadline_s:.3f}s exceeded")
                conn = self._checkout(remaining)
                _send_frame(conn, payload)
                sent_ok = True
                frame = _recv_frame(conn, deadline)
                if frame is None:
                    raise ConnectionError(f"peer {self.addr} closed")
                try:
                    resp = decode_msg(frame)
                except Exception as e:  # noqa: BLE001 — codec failure
                    raise ProtocolError(f"undecodable reply: {e}") from e
                self._checkin(conn)
                conn = None
                rtt = time.monotonic() - a0
                if obs is not None:
                    obs.record_success(rtt)
                sent = len(req) + 4
                recv = len(frame) + 4
                qmetrics.inc("rpc.calls", verb=method)
                qmetrics.inc("rpc.bytes", sent + recv, verb=method)
                qmetrics.observe("rpc.call_s", rtt, verb=method)
                if tspan is not None:
                    tspan.tags["retries"] = attempt
                    tspan.tags["bytes"] = sent + recv
                    rspans = resp.get("spans")
                    if rspans:
                        # the remote half of the tree (parented under
                        # this span via the sid we sent)
                        qtrace.absorb(tctx, rspans)
                if not resp.get("ok"):
                    # the handler ran and raised — a remote APPLICATION
                    # error, deterministic on resend: never retried here
                    raise RpcError(resp.get("error_kind", "Remote"),
                                   resp.get("error", ""))
                return resp.get("result"), sent, recv
            except (ConnectionError, OSError, ProtocolError) as e:
                # any mid-frame failure leaves the stream unusable:
                # close it (never back to the pool) so the next attempt
                # reconnects cleanly
                if conn is not None:
                    self._discard(conn)
                now = time.monotonic()
                if tspan is not None:
                    # failed attempts must still attribute their retry
                    # count — a terminal raise skips the success-path
                    # tagging (the last failing attempt is `attempt`)
                    tspan.tags["retries"] = attempt
                timed_out = isinstance(e, (socket.timeout,
                                           DeadlineExceeded)) \
                    or now >= deadline
                if obs is not None:
                    obs.record_failure()
                    if timed_out:
                        obs.record_deadline()
                # a request that never hit the wire is always safe to
                # retry; once SENT, only policy-declared idempotent
                # verbs may be resent (the reply may be the lost frame)
                may_retry = (not sent_ok) or pol.idempotent
                if not may_retry or attempt >= max(pol.max_retries, 1):
                    self._count_terminal(method, e, now, deadline)
                    err = self._at_deadline(e, method, now, deadline,
                                            deadline_s)
                    # whether the request hit the wire before dying:
                    # callers with their own retry ladders must not
                    # resend a non-idempotent verb once this is True
                    err.request_sent = sent_ok
                    raise err
                backoff = min(pol.backoff_base_s * (2 ** attempt),
                              pol.backoff_cap_s)
                backoff *= 0.5 + random.random()  # full jitter
                if now + backoff >= deadline:
                    self._count_terminal(method, e, now, deadline)
                    err = self._at_deadline(e, method, now, deadline,
                                            deadline_s)
                    err.request_sent = sent_ok
                    raise err
                time.sleep(backoff)
                attempt += 1
                qmetrics.inc("rpc.retries", verb=method)
                if obs is not None:
                    obs.record_retry()

    @staticmethod
    def _count_terminal(method: str, e: Exception, now: float,
                        deadline: float):
        qmetrics.inc("rpc.failures", verb=method)
        if isinstance(e, (socket.timeout, DeadlineExceeded)) \
                or now >= deadline:
            qmetrics.inc("rpc.deadline_exceeded", verb=method)

    def _at_deadline(self, e: Exception, method: str, now: float,
                     deadline: float, deadline_s: float) -> Exception:
        """Normalize a terminal failure: past the deadline every error
        becomes DeadlineExceeded (fail fast, one kind to handle)."""
        if isinstance(e, DeadlineExceeded):
            return e
        if now >= deadline or isinstance(e, socket.timeout):
            exc = DeadlineExceeded(
                f"{method} to {self.addr}: deadline "
                f"{deadline_s:.3f}s exceeded ({e})")
            exc.__cause__ = e
            return exc
        return e

    def ping(self, _deadline_s: float | None = None) -> bool:
        try:
            return self.call("ping", _deadline_s=_deadline_s) == "pong"
        except (OSError, RpcError):
            return False

    def close(self):
        """Drop every pooled connection (the client stays usable — the
        next call dials fresh, matching the old reconnect semantics)."""
        with self._lock:
            pool, self._pool = self._pool, []
            self._conns = max(self._conns - len(pool), 0)
            self._lock.notify_all()
        for s in pool:
            try:
                s.close()
            except OSError:
                pass
