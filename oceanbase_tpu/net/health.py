"""Cluster failure detector: heartbeats + per-peer EWMA three-state breaker.

Reference analog: the failure detector feeding palf election leases
(src/logservice/palf/election) and the server blacklist
(ObServerBlacklist, share/ob_server_blacklist.cpp) that routing layers
consult to steer requests away from flaky servers BEFORE paying a
timeout.

One `HealthMonitor` per node process.  Signal comes from two sources:

- a heartbeat thread pinging every peer each ``interval_s`` with a
  deadline tied to the period (a hung peer cannot stall the loop);
- every ordinary RPC outcome, via the per-peer observer installed on the
  peer's `RpcClient` (`record_success`/`record_failure`/...): real
  traffic keeps the detector fresher than heartbeats alone.

Per peer, a breaker walks three states on consecutive failures:

    up ──(fails ≥ suspect_after)──> suspect ──(fails ≥ down_after)──> down
     ^                                                                 │
     └──────────────────── any success ────────────────────────────────┘

Consumers:
- the DTL exchange routes slices AWAY from suspect/down peers
  pre-emptively (px/dtl.py) instead of paying the timeout-then-fallback;
- `NetPalf.on_peer_down` campaigns immediately when the known leader
  dies instead of waiting for its lease to expire (palf/netcluster.py);
- `gv$cluster_health` (server/virtual_tables.py) serves the table.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from oceanbase_tpu.server import metrics as qmetrics

UP, SUSPECT, DOWN = "up", "suspect", "down"

qmetrics.declare("health.transitions", "counter",
                 "failure-detector state flips (label: to=<state>)")
qmetrics.declare("health.breaker_opens", "counter",
                 "peers leaving the 'up' state")


@dataclass
class PeerHealth:
    """Mutable per-peer record — only ever touched under the monitor's
    lock (the heartbeat thread and every rpc caller thread race here)."""

    peer: int
    state: str = UP
    rtt_ewma_ms: float = 0.0
    consecutive_failures: int = 0
    breaker_opens: int = 0       # transitions out of "up"
    successes: int = 0
    failures: int = 0
    retries: int = 0
    deadline_exceeded: int = 0
    last_change_ts: float = 0.0   # monotonic, 0 = never
    last_transition_ts: float = 0.0  # wall clock of the last state flip

    def row(self) -> dict:
        return {"peer": self.peer, "state": self.state,
                "rtt_ewma_ms": self.rtt_ewma_ms,
                "consecutive_failures": self.consecutive_failures,
                "breaker_opens": self.breaker_opens,
                "successes": self.successes, "failures": self.failures,
                "retries": self.retries,
                "deadline_exceeded": self.deadline_exceeded,
                "last_transition_ts": self.last_transition_ts}


class _PeerObserver:
    """RpcClient-facing adapter: one per peer, feeds the monitor."""

    def __init__(self, monitor: "HealthMonitor", peer: int):
        self._monitor = monitor
        self._peer = peer

    def record_success(self, rtt_s: float):
        self._monitor.record_success(self._peer, rtt_s)

    def record_failure(self):
        self._monitor.record_failure(self._peer)

    def record_retry(self):
        self._monitor.record_retry(self._peer)

    def record_deadline(self):
        self._monitor.record_deadline(self._peer)


class HealthMonitor:
    def __init__(self, node_id: int, peers: dict, interval_s: float = 0.5,
                 suspect_after: int = 2, down_after: int = 4,
                 rtt_alpha: float = 0.2, on_down=None):
        """peers: {node_id: RpcClient}.  ``on_down(peer_id)`` fires (from
        the reporting thread, outside the lock) on each transition INTO
        down — the re-election / routing-invalidation hook."""
        self.node_id = node_id
        self.peers = peers
        self.interval_s = float(interval_s)
        self.suspect_after = int(suspect_after)
        self.down_after = int(down_after)
        self.rtt_alpha = float(rtt_alpha)
        self.on_down = on_down
        self._stats: dict[int, PeerHealth] = {
            pid: PeerHealth(pid) for pid in peers}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------------
    def observer(self, peer: int) -> _PeerObserver:
        with self._lock:
            if peer not in self._stats:
                self._stats[peer] = PeerHealth(peer)
        return _PeerObserver(self, peer)

    # -- signal sinks (any thread) -------------------------------------
    def record_success(self, peer: int, rtt_s: float):
        with self._lock:
            st = self._stats.get(peer)
            if st is None:
                return
            st.successes += 1
            st.consecutive_failures = 0
            ms = rtt_s * 1000.0
            st.rtt_ewma_ms = ms if st.rtt_ewma_ms == 0.0 else (
                self.rtt_alpha * ms
                + (1.0 - self.rtt_alpha) * st.rtt_ewma_ms)
            if st.state != UP:
                # breaker resets on the FIRST success: a recovered peer
                # flips down→up within one heartbeat interval, so DTL
                # routing (and gv$px_exchange avoided_parts) stop
                # steering around it promptly
                st.state = UP
                st.last_change_ts = time.monotonic()
                st.last_transition_ts = time.time()
                qmetrics.inc("health.transitions", to=UP)

    def record_failure(self, peer: int):
        fire = None
        with self._lock:
            st = self._stats.get(peer)
            if st is None:
                return
            st.failures += 1
            st.consecutive_failures += 1
            new = st.state
            if st.consecutive_failures >= self.down_after:
                new = DOWN
            elif st.consecutive_failures >= self.suspect_after:
                new = SUSPECT
            if new != st.state:
                if st.state == UP:
                    st.breaker_opens += 1
                    qmetrics.inc("health.breaker_opens")
                went_down = new == DOWN
                st.state = new
                st.last_change_ts = time.monotonic()
                st.last_transition_ts = time.time()
                qmetrics.inc("health.transitions", to=new)
                if went_down and self.on_down is not None:
                    fire = self.on_down
        if fire is not None:
            # the reporting thread may be a user statement mid-rpc (or a
            # palf caller already holding NetPalf._lock); the down hook
            # runs a staggered multi-round ELECTION — never make the
            # reporter pay for it (or deadlock on lock re-entry)
            threading.Thread(target=fire, args=(peer,), daemon=True,
                             name=f"on-down-{peer}").start()

    def record_retry(self, peer: int):
        with self._lock:
            st = self._stats.get(peer)
            if st is not None:
                st.retries += 1

    def record_deadline(self, peer: int):
        with self._lock:
            st = self._stats.get(peer)
            if st is not None:
                st.deadline_exceeded += 1

    # -- consumers -----------------------------------------------------
    def state(self, peer: int) -> str:
        with self._lock:
            st = self._stats.get(peer)
            return UP if st is None else st.state

    def live_peers(self) -> list[int]:
        with self._lock:
            return [p for p, st in self._stats.items() if st.state == UP]

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [self._stats[p].row() for p in sorted(self._stats)]

    # -- heartbeat loop ------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"health-{self.node_id}")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2 * self.interval_s + 1.0)
            self._thread = None

    def _run(self):
        # the ping's own observer wiring records the outcome; bounding
        # the deadline to the period keeps one dead peer from delaying
        # the next round by more than ~one interval
        while not self._stop.wait(self.interval_s):
            for pid, cli in list(self.peers.items()):
                if self._stop.is_set():
                    return
                if getattr(cli, "observer", None) is not None:
                    cli.ping(_deadline_s=self.interval_s)
                else:
                    # unwired client (tests): account the probe here
                    t0 = time.monotonic()
                    if cli.ping(_deadline_s=self.interval_s):
                        self.record_success(pid,
                                            time.monotonic() - t0)
                    else:
                        self.record_failure(pid)
