"""CDC: change-data-capture over the replicated WAL.

Reference analog: libobcdc (src/logservice/libobcdc) + cdcservice — a
pull-based pipeline turning committed log entries into ordered row-change
events.  Here the consumer polls the PALF leader's committed range,
buffers redo per transaction, and emits events at each commit record in
commit order (aborted transactions never surface).
"""

from __future__ import annotations

import json
from dataclasses import dataclass


@dataclass
class ChangeEvent:
    table: str
    op: str                 # insert | update | delete
    key: tuple
    values: dict
    commit_version: int
    tx_id: int
    lsn: int                # commit record's LSN


class CdcPump:
    """One consumer's cursor over a tenant's WAL (≙ obcdc instance)."""

    def __init__(self, tenant):
        self.tenant = tenant
        self.next_lsn = 0
        self._pending: dict[int, list] = {}

    def poll(self, max_events: int | None = None) -> list[ChangeEvent]:
        wal = self.tenant.wal
        ldr = wal.replicas[wal.leader_id]
        committed = ldr.committed_lsn
        out: list[ChangeEvent] = []
        if self.next_lsn < ldr.base_lsn:
            # WAL recycle dropped entries this cursor never consumed:
            # they were applied + checkpointed long ago — a consumer
            # this stale resumes at the recycle point (≙ obcdc falling
            # back to the archive when the online log is recycled)
            self.next_lsn = ldr.base_lsn
        while self.next_lsn < committed:
            e = ldr.entries[self.next_lsn - ldr.base_lsn]
            self.next_lsn += 1
            try:
                rec = json.loads(e.payload.decode())
            except Exception:
                continue
            op = rec.get("op")
            if op == "redo":
                self._pending.setdefault(rec["tx"], []).append(rec)
            elif op == "commit":
                for r in self._pending.pop(rec["tx"], []):
                    out.append(ChangeEvent(
                        table=r["table"], op=r["kind"],
                        key=tuple(r["key"]), values=r["values"],
                        commit_version=rec["version"], tx_id=rec["tx"],
                        lsn=e.lsn))
            elif op == "abort":
                self._pending.pop(rec["tx"], None)
            if max_events is not None and len(out) >= max_events:
                break
        return out
