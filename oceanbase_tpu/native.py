"""ctypes bridge to the native host-runtime kernels (native/).

Builds lazily with make on first import if the shared library is missing;
every entry point has a pure-numpy fallback so the framework works without
a toolchain (≙ the reference's portable fallbacks next to SIMD paths).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)),
                           "native")
_SO = os.path.join(_NATIVE_DIR, "libobtpu_native.so")

_lib = None
_lib_lock = threading.Lock()
_build_attempted = False


_MASK64 = (1 << 64) - 1


def _load():
    global _lib, _build_attempted
    if _lib is not None:  # lock-free fast path (hot on the WAL append path)
        return _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        if not os.path.exists(_SO) and not _build_attempted:
            _build_attempted = True
            try:
                subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                               capture_output=True, timeout=120)
            except Exception:
                return None
        if not os.path.exists(_SO):
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.obtpu_crc64.restype = ctypes.c_uint64
        lib.obtpu_crc64.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.c_uint64]
        u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        lib.obtpu_delta_varint_encode.restype = ctypes.c_uint64
        lib.obtpu_delta_varint_encode.argtypes = [
            i64p, ctypes.c_uint64, u8p, ctypes.c_uint64]
        lib.obtpu_delta_varint_decode.restype = ctypes.c_uint64
        lib.obtpu_delta_varint_decode.argtypes = [
            u8p, ctypes.c_uint64, i64p, ctypes.c_uint64]
        lib.obtpu_rle_runs_i64.restype = ctypes.c_uint64
        lib.obtpu_rle_runs_i64.argtypes = [
            i64p, ctypes.c_uint64, u64p, ctypes.c_uint64]
        u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
        bytep = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.obtpu_csv_tokenize.restype = ctypes.c_uint64
        lib.obtpu_csv_tokenize.argtypes = [
            bytep, ctypes.c_uint64, ctypes.c_uint8, ctypes.c_uint64,
            u64p, u32p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.obtpu_parse_int64_fields.restype = ctypes.c_uint64
        lib.obtpu_parse_int64_fields.argtypes = [
            bytep, u64p, u32p, ctypes.c_uint64, ctypes.c_int64, i64p,
            bytep]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# crc64 (log/segment integrity)
# ---------------------------------------------------------------------------

_PY_TABLE = None


def _py_crc64_table():
    global _PY_TABLE
    if _PY_TABLE is None:
        poly = np.uint64(0xC96C5795D7870F42)
        table = np.zeros(256, dtype=np.uint64)
        for i in range(256):
            crc = np.uint64(i)
            for _ in range(8):
                crc = (crc >> np.uint64(1)) ^ (
                    poly if crc & np.uint64(1) else np.uint64(0))
            table[i] = crc
        _PY_TABLE = table
    return _PY_TABLE


def crc64(data: bytes, seed: int = 0) -> int:
    lib = _load()
    if lib is not None:
        return int(lib.obtpu_crc64(data, len(data), seed))
    # numpy fallback (byte-at-a-time through the table)
    table = _py_crc64_table()
    crc = np.uint64(~seed & 0xFFFFFFFFFFFFFFFF)
    for b in data:
        crc = table[int((crc ^ np.uint64(b)) & np.uint64(0xFF))] ^ \
            (crc >> np.uint64(8))
    return int(~crc & 0xFFFFFFFFFFFFFFFF)


# ---------------------------------------------------------------------------
# delta + zigzag + varint codec (segment persistence)
# ---------------------------------------------------------------------------


def delta_varint_encode(values: np.ndarray) -> bytes:
    values = np.ascontiguousarray(values, dtype=np.int64)
    lib = _load()
    if lib is not None:
        out = np.empty(len(values) * 10 + 16, dtype=np.uint8)
        n = int(lib.obtpu_delta_varint_encode(values, len(values), out,
                                              len(out)))
        if n:
            return out[:n].tobytes()
    # python fallback: deltas in wrapping 64-bit arithmetic (matches the
    # native codec for full-range values like MAX-MIN)
    out_b = bytearray()
    prev = 0
    for v in values.tolist():
        d = (v - prev) & _MASK64
        if d >= 1 << 63:
            d -= 1 << 64  # back to signed
        u = ((d << 1) ^ (d >> 63)) & _MASK64
        prev = v
        while True:
            b = u & 0x7F
            u >>= 7
            out_b.append(b | (0x80 if u else 0))
            if not u:
                break
    return bytes(out_b)


def delta_varint_decode(buf: bytes, n: int) -> np.ndarray:
    if n == 0:
        return np.zeros(0, dtype=np.int64)
    lib = _load()
    if lib is not None:
        arr = np.frombuffer(buf, dtype=np.uint8)
        out = np.empty(n, dtype=np.int64)
        used = int(lib.obtpu_delta_varint_decode(
            np.ascontiguousarray(arr), len(arr), out, n))
        if used == 0:
            raise ValueError("corrupt varint payload (native decode failed)")
        return out
    out_l = np.empty(n, dtype=np.int64)
    pos = 0
    prev = 0
    try:
        for i in range(n):
            u = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                u |= (b & 0x7F) << shift
                if not (b & 0x80):
                    break
                shift += 7
                if shift > 63:
                    raise ValueError("corrupt varint payload")
            d = (u >> 1) ^ -(u & 1)
            prev = (prev + d) & _MASK64
            if prev >= 1 << 63:
                prev -= 1 << 64
            out_l[i] = prev
    except IndexError:
        raise ValueError("corrupt varint payload (truncated)") from None
    return out_l


# ---------------------------------------------------------------------------
# CSV tokenizer + field parsers (direct-load fast path; python csv module
# remains the fallback and the oracle for quoting semantics)
# ---------------------------------------------------------------------------


def csv_tokenize(data: bytes, n_cols: int, delimiter: str = ","):
    """-> (buf, offsets[n_rows*n_cols], lengths, n_rows) or None when the
    native library is unavailable or the file is ragged (caller falls
    back to the python csv module)."""
    lib = _load()
    if lib is None:
        return None
    buf = np.frombuffer(data, dtype=np.uint8)
    # upper bound on rows: every row ends with \n or a lone \r (counting
    # \r\n twice only over-allocates)
    approx_rows = data.count(b"\n") + data.count(b"\r") + 2
    offsets = np.empty(approx_rows * n_cols, dtype=np.uint64)
    lengths = np.empty(approx_rows * n_cols, dtype=np.uint32)
    err = ctypes.c_uint64(0)
    n_rows = int(lib.obtpu_csv_tokenize(
        np.ascontiguousarray(buf), len(buf), ord(delimiter), n_cols,
        offsets, lengths, approx_rows, ctypes.byref(err)))
    if n_rows == 0 and err.value:
        return None
    return buf, offsets[:n_rows * n_cols], lengths[:n_rows * n_cols], n_rows


def parse_int64_fields(buf: np.ndarray, offsets, lengths,
                       scale: int = 0):
    """Batch-parse tokenized fields into scaled int64 + validity."""
    lib = _load()
    n = len(offsets)
    out = np.empty(n, dtype=np.int64)
    valid = np.empty(n, dtype=np.uint8)
    if lib is None:
        for i in range(n):
            ln = int(lengths[i]) & 0x7FFFFFFF
            s = bytes(buf[int(offsets[i]):int(offsets[i]) + ln]).decode()
            try:
                if scale:
                    from decimal import Decimal

                    out[i] = int(Decimal(s).scaleb(scale))
                else:
                    out[i] = int(s)
                valid[i] = 1
            except Exception:  # noqa: BLE001
                out[i] = 0
                valid[i] = 0
        return out, valid.astype(bool)
    lib.obtpu_parse_int64_fields(
        np.ascontiguousarray(buf), np.ascontiguousarray(offsets),
        np.ascontiguousarray(lengths), n, 10 ** scale, out, valid)
    return out, valid.astype(bool)


def field_strings(buf, offsets, lengths) -> np.ndarray:
    """Materialize tokenized fields as python strings (unescaping the rare
    quoted-quote fields flagged in the length high bit).  ``buf`` may be
    the original bytes object (no copy) or a uint8 array."""
    out = np.empty(len(offsets), dtype=object)
    data = buf if isinstance(buf, (bytes, bytearray)) else buf.tobytes()
    for i in range(len(offsets)):
        ln = int(lengths[i])
        esc = bool(ln & 0x80000000)
        ln &= 0x7FFFFFFF
        o = int(offsets[i])
        s = data[o:o + ln].decode(errors="replace")
        out[i] = s.replace('""', '"') if esc else s
    return out


def rle_run_starts(values: np.ndarray) -> np.ndarray:
    values = np.ascontiguousarray(values, dtype=np.int64)
    lib = _load()
    if lib is not None:
        starts = np.empty(len(values), dtype=np.uint64)
        n = int(lib.obtpu_rle_runs_i64(values, len(values), starts,
                                       len(starts)))
        return starts[:n].astype(np.int64)
    if len(values) == 0:
        return np.zeros(0, dtype=np.int64)
    change = np.empty(len(values), dtype=bool)
    change[0] = True
    np.not_equal(values[1:], values[:-1], out=change[1:])
    return np.nonzero(change)[0]
