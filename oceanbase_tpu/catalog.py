"""Catalog: schemas, tables, statistics.

Reference analog: the schema service (src/share/schema,
ObMultiVersionSchemaService src/share/schema/ob_multi_version_schema_service.h:151)
plus optimizer statistics (src/share/stat).  Round-1 scope: an in-memory
catalog versioned by a monotonically increasing schema version; the storage
engine (storage/) persists and reloads it.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from oceanbase_tpu.datatypes import SqlType, TypeKind
from oceanbase_tpu.vector import Relation, from_numpy


@dataclass
class ColumnDef:
    name: str
    dtype: SqlType
    nullable: bool = True


@dataclass
class IndexDef:
    """A secondary index (≙ index-table schema, ObTableSchema with
    INDEX_TYPE_NORMAL/UNIQUE — src/share/schema/ob_table_schema.h).

    Stored as its own index TABLE whose key is (index columns + primary
    key columns) — the index-table model OceanBase uses, riding the same
    tablet/WAL/MVCC machinery as any table.  ``storage_table`` names it.
    """

    name: str
    table: str
    columns: list[str]
    unique: bool
    storage_table: str


@dataclass
class TableDef:
    name: str
    columns: list[ColumnDef]
    primary_key: list[str] = field(default_factory=list)
    # optimizer stats (≙ src/share/stat basic table stats)
    row_count: int = 0
    ndv: dict[str, int] = field(default_factory=dict)
    # equi-height histograms from ANALYZE: col -> (edges ndarray in the
    # STORAGE value domain, null_fraction) — ≙ ObOptColumnStat histogram
    # (src/share/stat/ob_opt_column_stat.h)
    histograms: dict = field(default_factory=dict)
    # most-common-values lists from ANALYZE for dict-encoded string
    # columns: col -> (values list, frequency-fraction list) — string
    # equality selectivity reads the measured frequency instead of a
    # guess (≙ ObOptColumnStat top-k frequency histogram)
    mcv: dict = field(default_factory=dict)
    # range partitioning: (column, [upper-exclusive split points]) or None
    partition: tuple | None = None
    auto_increment_cols: list = field(default_factory=list)
    indexes: list = field(default_factory=list)  # list[IndexDef]
    # vector/fulltext indexes: name -> {"kind", "column", "metric"...}
    # (runtime structures — IVF buckets, posting lists — rebuild lazily
    # per data_version; ≙ INDEX_TYPE_VEC_* / INDEX_TYPE_FTS_* schemas)
    aux_indexes: dict = field(default_factory=dict)

    def column(self, name: str) -> ColumnDef:
        for c in self.columns:
            if c.name == name:
                return c
        raise KeyError(name)

    def has_column(self, name: str) -> bool:
        return any(c.name == name for c in self.columns)

    @property
    def column_names(self) -> list[str]:
        return [c.name for c in self.columns]


def sampled_ndv(arr, n: int, sample: int = 8192) -> int:
    """NDV estimate from a fixed-seed sample (load-time default stats;
    ANALYZE refines with the exact count).  A saturating sample (few
    distinct values) means a low-cardinality domain — report the sample
    distinct count, not a scaled guess: nationkey-style columns must not
    look like high-cardinality keys to the join-order cost model."""
    import numpy as _np

    if n == 0:
        return 1
    if n <= sample:
        return max(1, int(len(_np.unique(arr[:n]))))
    idx = _np.random.default_rng(0).choice(n, sample, replace=False)
    d = int(len(_np.unique(arr[idx])))
    if d <= sample // 2:
        return max(d, 1)
    return max(1, min(n, int(d * (n / sample))))


class Catalog:
    """Named tables -> (definition, device-resident data).

    Thread-safe; schema_version bumps on DDL (≙ schema refresh protocol)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._defs: dict[str, TableDef] = {}
        self._data: dict[str, Relation] = {}
        # transient tables: materialized virtual (gv$/v$) relations,
        # refreshed per statement (≙ virtual table iterators)
        self._transients: dict[str, tuple] = {}
        # external (lake) tables: name -> {"tdef", "location", "format",
        # "delimiter", "skip", "cache": (mtime, Relation)|None}
        # (≙ src/share/external_table — files scanned at query time)
        self._externals: dict[str, dict] = {}
        # views: name -> {"sql": body text, "cols": [alias...]|[]}
        # (≙ __all_view view_definition; expanded at bind time)
        self._views: dict[str, dict] = {}
        self.schema_version = 1

    # -- views ------------------------------------------------------------
    def create_view(self, name: str, sql: str, cols=None,
                    or_replace: bool = False):
        with self._lock:
            if self.has_table(name) or name in self._externals:
                raise ValueError(f"table {name} already exists")
            if name in self._views and not or_replace:
                raise ValueError(f"view {name} already exists")
            self._views[name] = {"sql": sql, "cols": list(cols or [])}
            self.schema_version += 1

    def drop_view(self, name: str) -> bool:
        with self._lock:
            if self._views.pop(name, None) is None:
                return False
            self.schema_version += 1
            return True

    def view_def(self, name: str):
        with self._lock:
            return self._views.get(name)

    def view_names(self) -> list[str]:
        with self._lock:
            return sorted(self._views)

    def drop_transient(self, name: str):
        with self._lock:
            self._transients.pop(name, None)

    # -- external tables --------------------------------------------------
    def register_external(self, tdef: TableDef, location: str,
                          fmt: str = "csv", delimiter: str = ",",
                          skip_lines: int = 0,
                          if_not_exists: bool = False):
        with self._lock:
            if tdef.name in self._externals:
                if if_not_exists:
                    return
                raise ValueError(f"external table {tdef.name} exists")
            # collision checks inside ONE locked section (no
            # check-then-act window against concurrent DDL); has_table()
            # stays virtual — StorageCatalog covers WAL-applied engine
            # tables the base maps don't know about
            if self.has_table(tdef.name):
                raise ValueError(f"table {tdef.name} already exists")
            if self.view_def(tdef.name) is not None:
                raise ValueError(f"view {tdef.name} already exists")
            self._externals[tdef.name] = {
                "tdef": tdef, "location": location, "format": fmt,
                "delimiter": delimiter, "skip": skip_lines,
                "cache": None}
            self.schema_version += 1

    def drop_external(self, name: str) -> bool:
        with self._lock:
            if self._externals.pop(name, None) is not None:
                self.schema_version += 1
                return True
            return False

    def _external_lookup(self, name: str):
        return self._externals.get(name)

    def _external_data(self, name: str) -> Relation:
        import os as _os

        from oceanbase_tpu.share.external import read_external

        e = self._externals.get(name)
        if e is None:  # dropped concurrently: the normal missing-table path
            raise KeyError(f"unknown table {name}")
        try:
            mtime = _os.path.getmtime(e["location"])
        except OSError:
            mtime = None
        with self._lock:
            hit = e["cache"]
            if hit is not None and hit[0] == mtime:
                return hit[1]
        arrays, valids, types = read_external(
            e["location"], e["format"], e["tdef"], e["delimiter"],
            e["skip"])
        rel = from_numpy(arrays, types=types, valids=valids or None)
        with self._lock:
            e["cache"] = (mtime, rel)
            e["tdef"].row_count = rel.capacity
        return rel

    def register_transient(self, name: str, arrays, types=None,
                           valids=None):
        from oceanbase_tpu.vector import empty_relation, from_numpy

        n = len(next(iter(arrays.values()))) if arrays else 0
        if n == 0:
            # static shapes need capacity >= 1: one all-dead row
            def infer(v):
                kind = np.asarray(v).dtype.kind
                if kind in "OUS":
                    return SqlType.string()
                if kind == "f":
                    return SqlType.double()
                if kind == "b":
                    return SqlType.bool_()
                return SqlType.int_()

            col_types = {k: (types or {}).get(k) or infer(v)
                         for k, v in arrays.items()}
            rel = empty_relation(col_types)
            row_count = 0
        else:
            rel = from_numpy(arrays, types=types, valids=valids or None)
            row_count = rel.capacity
        cols = [ColumnDef(c, rel.columns[c].dtype) for c in arrays]
        tdef = TableDef(name, cols, row_count=max(row_count, 1))
        with self._lock:
            # symmetric to register_external: a transient must not
            # shadow a view (re-registering an existing transient is the
            # normal per-statement gv$ refresh and stays allowed)
            if self.view_def(name) is not None:
                raise ValueError(f"view {name} already exists")
            self._transients[name] = (tdef, rel)

    # -- DDL -------------------------------------------------------------
    def create_table(self, tdef: TableDef, if_not_exists: bool = False):
        with self._lock:
            # view-collision check INSIDE the locked section: a
            # concurrent CREATE VIEW between check and insert must not
            # leave a table shadowing a view (create_view holds the same
            # lock, so check+insert is atomic against it)
            if self.view_def(tdef.name) is not None:
                raise ValueError(f"view {tdef.name} already exists")
            if tdef.name in self._defs or tdef.name in self._externals:
                if if_not_exists:
                    return
                raise ValueError(f"table {tdef.name} already exists")
            self._defs[tdef.name] = tdef
            self.schema_version += 1

    def drop_table(self, name: str, if_exists: bool = False):
        with self._lock:
            if name not in self._defs:
                if if_exists:
                    return
                raise KeyError(name)
            del self._defs[name]
            self._data.pop(name, None)
            self.schema_version += 1

    # -- data ------------------------------------------------------------
    def load_numpy(self, name: str, arrays: dict[str, np.ndarray],
                   types: dict[str, SqlType] | None = None,
                   primary_key: list[str] | None = None,
                   valids: dict[str, np.ndarray] | None = None):
        """Bulk-load host arrays as a table (≙ direct load path,
        src/storage/direct_load)."""
        rel = from_numpy(arrays, types=types, valids=valids)
        n = rel.capacity
        cols = []
        ndv = {}
        for cname in arrays:
            col = rel.columns[cname]
            cols.append(ColumnDef(cname, col.dtype, nullable=col.valid is not None))
            if col.sdict is not None:
                ndv[cname] = col.sdict.size
            elif col.dtype.kind == TypeKind.VECTOR:
                ndv[cname] = n
            else:
                ndv[cname] = sampled_ndv(np.asarray(arrays[cname]), n)
        with self._lock:
            self._defs[name] = TableDef(
                name, cols, primary_key=primary_key or [], row_count=n, ndv=ndv
            )
            self._data[name] = rel
            self.schema_version += 1

    def set_data(self, name: str, rel: Relation):
        with self._lock:
            self._data[name] = rel
            d = self._defs.get(name)
            if d is not None:
                d.row_count = rel.capacity

    # -- lookup ----------------------------------------------------------
    def table_def(self, name: str) -> TableDef:
        with self._lock:
            t = self._transients.get(name)
            if t is not None:
                return t[0]
            e = self._externals.get(name)
            if e is not None:
                return e["tdef"]
            if name not in self._defs:
                raise KeyError(f"unknown table {name}")
            return self._defs[name]

    def table_data(self, name: str) -> Relation:
        with self._lock:
            t = self._transients.get(name)
            if t is not None:
                return t[1]
        if name in self._externals:
            return self._external_data(name)
        with self._lock:
            if name not in self._data:
                raise KeyError(f"table {name} has no data")
            return self._data[name]

    def has_table(self, name: str) -> bool:
        with self._lock:
            return name in self._defs or name in self._transients or \
                name in self._externals

    def tables(self) -> list[str]:
        with self._lock:
            # index storage tables are internal (reachable by name, but
            # hidden from SHOW TABLES / information_schema enumeration)
            return sorted([n for n in self._defs
                           if not n.startswith("__idx__")]
                          + list(self._externals))
