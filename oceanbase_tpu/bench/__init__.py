"""Benchmark harness: TPC-H data generation + query suite.

The reference's headline numbers are TPC-H/TPC-C (README.md:44); the
driver's BASELINE.json ladder is TPC-H Q6/Q1/Q14/Q9 then the 22-query
suite.  ``tpch.py`` is a vectorized numpy dbgen analog (self-consistent
schema + distributions approximating the spec closely enough that every
query has non-degenerate selectivity); correctness is checked against a
SQLite oracle on the same generated data (≙ mysqltest result diffing,
tools/deploy/mysql_test).
"""
