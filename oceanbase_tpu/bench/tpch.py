"""Vectorized TPC-H data generator (dbgen analog).

Produces the 8-table schema with the spec's row-count scaling and close
approximations of the value distributions that drive query selectivity
(dates, discounts, quantities, brands/types/containers, comment trigger
words for the LIKE queries).  All columns are generated as numpy arrays —
at SF1 this builds ~6M lineitem rows in a few seconds.

Decimals are generated as scaled int64 (cents / basis points) to match the
engine's fixed-point representation (see datatypes.py).
"""

from __future__ import annotations

import numpy as np

from oceanbase_tpu.datatypes import SqlType, date_to_days

# ---------------------------------------------------------------------------
# vocabulary (subset of the spec's grammar, enough for LIKE selectivities)
# ---------------------------------------------------------------------------

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

COLORS = (
    "almond antique aquamarine azure beige bisque black blanched blue blush "
    "brown burlywood burnished chartreuse chiffon chocolate coral cornflower "
    "cornsilk cream cyan dark deep dim dodger drab firebrick floral forest "
    "frosted gainsboro ghost goldenrod green grey honeydew hot indian ivory "
    "khaki lace lavender lawn lemon light lime linen magenta maroon medium "
    "metallic midnight mint misty moccasin navajo navy olive orange orchid "
    "pale papaya peach peru pink plum powder puff purple red rose rosy royal "
    "saddle salmon sandy seashell sienna sky slate smoke snow spring steel "
    "tan thistle tomato turquoise violet wheat white yellow"
).split()

TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]

CONTAINER_S1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_S2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]

_COMMENT_WORDS = (
    "the of and to in that was his he it with is for as had you not be her "
    "on at by which have or from this him but all she they were my are me "
    "one their so an said them we who would been will no when there if more "
    "out up into do any your what has man could other than our some very "
    "time upon about may its only now like little then can made should did "
    "us such great before must two these seen know over much down after "
    "first mr good men own never most old shall day where those came come "
    "himself way work life without go make well through being went left "
    "again while last might us place found thought quickly carefully "
    "furiously slyly blithely quietly deposits requests instructions "
    "accounts packages ideas theodolites pinto beans foxes dependencies "
    "excuses platelets asymptotes courts dolphins multipliers sauternes "
    "warthogs frets dinos attainments somas braids pains grouches wheat "
    "special pending regular express unusual final ironic even bold silent"
).split()


def _comment_pool(rng, pool_size: int, trigger=None, trigger_frac=0.009):
    """Build a pool of comment strings; optionally seed `trigger` phrases
    ('word1%word2' -> both words in order) at the given fraction."""
    lens = rng.integers(4, 9, pool_size)
    words = rng.choice(np.array(_COMMENT_WORDS), (pool_size, 9))
    out = np.empty(pool_size, dtype=object)
    for i in range(pool_size):
        out[i] = " ".join(words[i, : lens[i]])
    if trigger:
        w1, w2 = trigger
        k = max(1, int(pool_size * trigger_frac))
        idx = rng.choice(pool_size, k, replace=False)
        for i in idx:
            out[i] = out[i] + f" {w1} extra {w2}"
    return out


def _money(rng, lo_cents, hi_cents, n):
    return rng.integers(lo_cents, hi_cents, n, dtype=np.int64)


D = date_to_days
_START = D("1992-01-01")
_END = D("1998-08-02")
_CURRENT = D("1995-06-17")


def gen_tpch(sf: float = 0.01, seed: int = 19920101):
    """Generate all 8 tables; returns (tables, types) where tables maps
    table -> {column -> numpy array} and types maps column -> SqlType."""
    rng = np.random.default_rng(seed)
    n_part = int(200_000 * sf)
    n_supp = max(int(10_000 * sf), 10)
    n_cust = int(150_000 * sf)
    n_ord = int(1_500_000 * sf)

    types: dict[str, SqlType] = {}
    tables: dict[str, dict[str, np.ndarray]] = {}

    # ---- region / nation ------------------------------------------------
    tables["region"] = {
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": np.array(REGIONS, dtype=object),
        "r_comment": _comment_pool(rng, 5),
    }
    nname = np.array([n for n, _ in NATIONS], dtype=object)
    nreg = np.array([r for _, r in NATIONS], dtype=np.int64)
    tables["nation"] = {
        "n_nationkey": np.arange(25, dtype=np.int64),
        "n_name": nname,
        "n_regionkey": nreg,
        "n_comment": _comment_pool(rng, 25),
    }

    # ---- supplier -------------------------------------------------------
    s_comment_pool = _comment_pool(
        rng, max(200, n_supp // 10), trigger=("Customer", "Complaints"),
        trigger_frac=0.005,
    )
    tables["supplier"] = {
        "s_suppkey": np.arange(1, n_supp + 1, dtype=np.int64),
        "s_name": np.array([f"Supplier#{i:09d}" for i in range(1, n_supp + 1)],
                           dtype=object),
        "s_address": _comment_pool(rng, max(100, n_supp // 20))[
            rng.integers(0, max(100, n_supp // 20), n_supp)],
        "s_nationkey": rng.integers(0, 25, n_supp, dtype=np.int64),
        "s_phone": np.array(
            [f"{rng.integers(10, 35)}-{rng.integers(100, 999)}-{rng.integers(100, 999)}-{rng.integers(1000, 9999)}"
             for _ in range(n_supp)], dtype=object),
        "s_acctbal": _money(rng, -99999, 999999, n_supp),
        "s_comment": s_comment_pool[rng.integers(0, len(s_comment_pool), n_supp)],
    }
    types["s_acctbal"] = SqlType.decimal(15, 2)

    # ---- part -----------------------------------------------------------
    pname_words = rng.choice(np.array(COLORS), (n_part, 5))
    p_name = np.array([" ".join(row) for row in pname_words], dtype=object)
    p_mfgr_i = rng.integers(1, 6, n_part)
    p_brand_i = p_mfgr_i * 10 + rng.integers(1, 6, n_part)
    p_type = (
        np.char.add(
            np.char.add(
                rng.choice(np.array(TYPE_S1), n_part).astype("U16"), " "
            ),
            np.char.add(
                np.char.add(rng.choice(np.array(TYPE_S2), n_part).astype("U16"), " "),
                rng.choice(np.array(TYPE_S3), n_part).astype("U16"),
            ),
        )
    ).astype(object)
    p_container = np.char.add(
        np.char.add(rng.choice(np.array(CONTAINER_S1), n_part).astype("U8"), " "),
        rng.choice(np.array(CONTAINER_S2), n_part).astype("U8"),
    ).astype(object)
    p_retail = (90000 + ((np.arange(1, n_part + 1) // 10) % 20001)
                + 100 * (np.arange(1, n_part + 1) % 1000)).astype(np.int64)
    tables["part"] = {
        "p_partkey": np.arange(1, n_part + 1, dtype=np.int64),
        "p_name": p_name,
        "p_mfgr": np.array([f"Manufacturer#{i}" for i in p_mfgr_i], dtype=object),
        "p_brand": np.array([f"Brand#{i}" for i in p_brand_i], dtype=object),
        "p_type": p_type,
        "p_size": rng.integers(1, 51, n_part, dtype=np.int64),
        "p_container": p_container,
        "p_retailprice": p_retail,
        "p_comment": _comment_pool(rng, max(100, n_part // 50))[
            rng.integers(0, max(100, n_part // 50), n_part)],
    }
    types["p_retailprice"] = SqlType.decimal(15, 2)

    # ---- partsupp (4 suppliers per part) --------------------------------
    n_ps = n_part * 4
    ps_partkey = np.repeat(np.arange(1, n_part + 1, dtype=np.int64), 4)
    ps_suppkey = (
        (ps_partkey + (np.tile(np.arange(4), n_part))
         * ((n_supp // 4) + 1)) % n_supp + 1
    ).astype(np.int64)
    tables["partsupp"] = {
        "ps_partkey": ps_partkey,
        "ps_suppkey": ps_suppkey,
        "ps_availqty": rng.integers(1, 10000, n_ps, dtype=np.int64),
        "ps_supplycost": _money(rng, 100, 100001, n_ps),
        "ps_comment": _comment_pool(rng, 200)[rng.integers(0, 200, n_ps)],
    }
    types["ps_supplycost"] = SqlType.decimal(15, 2)

    # ---- customer -------------------------------------------------------
    tables["customer"] = {
        "c_custkey": np.arange(1, n_cust + 1, dtype=np.int64),
        "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n_cust + 1)],
                           dtype=object),
        "c_address": _comment_pool(rng, max(100, n_cust // 30))[
            rng.integers(0, max(100, n_cust // 30), n_cust)],
        "c_nationkey": rng.integers(0, 25, n_cust, dtype=np.int64),
        "c_phone": np.array(
            [f"{10 + (i % 25)}-{100 + (i * 7) % 900}-{100 + (i * 13) % 900}-{1000 + (i * 31) % 9000}"
             for i in range(1, n_cust + 1)], dtype=object),
        "c_acctbal": _money(rng, -99999, 999999, n_cust),
        "c_mktsegment": rng.choice(np.array(SEGMENTS), n_cust).astype(object),
        "c_comment": _comment_pool(rng, max(200, n_cust // 30))[
            rng.integers(0, max(200, n_cust // 30), n_cust)],
    }
    types["c_acctbal"] = SqlType.decimal(15, 2)

    # ---- orders ---------------------------------------------------------
    # spec: only 2/3 of customers have orders (clustered on odd custkeys)
    o_orderkey = np.arange(1, n_ord + 1, dtype=np.int64)
    o_custkey = rng.integers(1, max(n_cust, 2), n_ord, dtype=np.int64)
    o_custkey = np.where(o_custkey % 3 == 0, np.maximum(o_custkey - 1, 1), o_custkey)
    o_orderdate = rng.integers(_START, _END - 151, n_ord, dtype=np.int64)
    o_comment_pool = _comment_pool(
        rng, max(500, n_ord // 100), trigger=("special", "requests"),
        trigger_frac=0.01,
    )
    tables["orders"] = {
        "o_orderkey": o_orderkey,
        "o_custkey": o_custkey,
        "o_orderstatus": np.empty(n_ord, dtype=object),  # filled below
        "o_totalprice": np.zeros(n_ord, dtype=np.int64),  # filled below
        "o_orderdate": o_orderdate.astype(np.int32),
        "o_orderpriority": rng.choice(np.array(PRIORITIES), n_ord).astype(object),
        "o_clerk": np.array([f"Clerk#{i:09d}" for i in
                             rng.integers(1, max(n_ord // 1000, 2), n_ord)],
                            dtype=object),
        "o_shippriority": np.zeros(n_ord, dtype=np.int64),
        "o_comment": o_comment_pool[rng.integers(0, len(o_comment_pool), n_ord)],
    }
    types["o_orderdate"] = SqlType.date()
    types["o_totalprice"] = SqlType.decimal(15, 2)

    # ---- lineitem -------------------------------------------------------
    n_lines = rng.integers(1, 8, n_ord)
    n_li = int(n_lines.sum())
    l_orderkey = np.repeat(o_orderkey, n_lines)
    l_odate = np.repeat(o_orderdate, n_lines)
    l_linenumber = (np.arange(n_li) -
                    np.repeat(np.cumsum(n_lines) - n_lines, n_lines) + 1)
    l_partkey = rng.integers(1, max(n_part, 2), n_li, dtype=np.int64)
    # supplier consistent with partsupp: one of the 4 suppliers of the part
    j = rng.integers(0, 4, n_li)
    l_suppkey = ((l_partkey + j * ((n_supp // 4) + 1)) % n_supp + 1).astype(np.int64)
    l_quantity = rng.integers(1, 51, n_li, dtype=np.int64) * 100  # scale 2
    l_extendedprice = (l_quantity // 100) * p_retail[l_partkey - 1]
    l_discount = rng.integers(0, 11, n_li, dtype=np.int64)  # scale 2: 0.00-0.10
    l_tax = rng.integers(0, 9, n_li, dtype=np.int64)
    l_shipdate = l_odate + rng.integers(1, 122, n_li)
    l_commitdate = l_odate + rng.integers(30, 91, n_li)
    l_receiptdate = l_shipdate + rng.integers(1, 31, n_li)
    l_linestatus = np.where(l_shipdate > _CURRENT, "O", "F").astype(object)
    rf = rng.integers(0, 2, n_li)
    l_returnflag = np.where(
        l_receiptdate <= _CURRENT, np.where(rf == 0, "R", "A"), "N"
    ).astype(object)
    tables["lineitem"] = {
        "l_orderkey": l_orderkey,
        "l_partkey": l_partkey,
        "l_suppkey": l_suppkey,
        "l_linenumber": l_linenumber.astype(np.int64),
        "l_quantity": l_quantity,
        "l_extendedprice": l_extendedprice,
        "l_discount": l_discount,
        "l_tax": l_tax,
        "l_returnflag": l_returnflag,
        "l_linestatus": l_linestatus,
        "l_shipdate": l_shipdate.astype(np.int32),
        "l_commitdate": l_commitdate.astype(np.int32),
        "l_receiptdate": l_receiptdate.astype(np.int32),
        "l_shipinstruct": rng.choice(np.array(SHIPINSTRUCT), n_li).astype(object),
        "l_shipmode": rng.choice(np.array(SHIPMODES), n_li).astype(object),
        "l_comment": _comment_pool(rng, 500)[rng.integers(0, 500, n_li)],
    }
    for c in ("l_quantity", "l_extendedprice"):
        types[c] = SqlType.decimal(15, 2)
    types["l_discount"] = SqlType.decimal(15, 2)
    types["l_tax"] = SqlType.decimal(15, 2)
    for c in ("l_shipdate", "l_commitdate", "l_receiptdate"):
        types[c] = SqlType.date()

    # back-fill orders totals/status from lineitem
    disc_price = l_extendedprice * (100 - l_discount) // 100
    charged = disc_price * (100 + l_tax) // 100
    o_total = np.zeros(n_ord + 1, dtype=np.int64)
    np.add.at(o_total, l_orderkey, charged)
    tables["orders"]["o_totalprice"] = o_total[1:]
    all_f = np.ones(n_ord + 1, dtype=bool)
    any_f = np.zeros(n_ord + 1, dtype=bool)
    isf = l_linestatus == "F"
    np.logical_and.at(all_f, l_orderkey, isf)
    np.logical_or.at(any_f, l_orderkey, isf)
    tables["orders"]["o_orderstatus"] = np.where(
        all_f[1:], "F", np.where(any_f[1:], "P", "O")
    ).astype(object)

    return tables, types


TPCH_PRIMARY_KEYS = {
    "region": ["r_regionkey"],
    "nation": ["n_nationkey"],
    "supplier": ["s_suppkey"],
    "part": ["p_partkey"],
    "partsupp": ["ps_partkey", "ps_suppkey"],
    "customer": ["c_custkey"],
    "orders": ["o_orderkey"],
    "lineitem": ["l_orderkey", "l_linenumber"],
}
