"""SQLite oracle for result-parity testing.

≙ the reference's mysqltest result diffing against a known-good engine
(tools/deploy/mysql_test, SURVEY §4 tier 4).  Loads the generated TPC-H
data into an in-memory SQLite database and translates our MySQL-ish SQL
into SQLite's dialect (date literals/arithmetic, EXTRACT, SUBSTRING).
"""

from __future__ import annotations

import re
import sqlite3

import numpy as np

from oceanbase_tpu.datatypes import SqlType, TypeKind, days_to_date


def load_sqlite(tables: dict, types: dict) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    for name, cols in tables.items():
        colnames = list(cols)
        decls = ", ".join(colnames)
        conn.execute(f"create table {name} ({decls})")
        n = len(next(iter(cols.values())))
        pycols = []
        for c in colnames:
            arr = cols[c]
            t = types.get(c)
            if t is not None and t.kind == TypeKind.DECIMAL:
                pycols.append([v / (10 ** t.scale) for v in arr.tolist()])
            elif t is not None and t.kind == TypeKind.DATE:
                pycols.append([days_to_date(int(v)) for v in arr])
            elif arr.dtype == object or arr.dtype.kind in "US":
                pycols.append([str(v) for v in arr])
            else:
                pycols.append(arr.tolist())
        rows = list(zip(*pycols))
        ph = ",".join("?" * len(colnames))
        conn.executemany(f"insert into {name} values ({ph})", rows)
    # index every *key column (PKs and FKs) so correlated subqueries and
    # joins in the ORACLE don't go quadratic at SF>=0.1 — the oracle's
    # job is to be correct AND fast enough to produce SF1 evidence
    for name, cols in tables.items():
        for c in cols:
            if c.endswith("key"):
                conn.execute(
                    f"create index idx_{name}_{c} on {name} ({c})")
    conn.execute("analyze")
    conn.commit()
    return conn


_DATE_RE = re.compile(r"date\s+'([0-9-]+)'", re.I)
_INTERVAL_RE = re.compile(
    r"'([0-9-]+)'\s*([+-])\s*interval\s+'(\d+)'\s+(year|month|day)", re.I)
_EXTRACT_RE = re.compile(r"extract\s*\(\s*year\s+from\s+([a-z0-9_.]+)\s*\)", re.I)
_SUBSTR_RE = re.compile(
    r"substring\s*\(\s*([a-z0-9_.]+)\s+from\s+(\d+)\s+for\s+(\d+)\s*\)", re.I)


def to_sqlite_sql(sql: str) -> str:
    s = _DATE_RE.sub(r"'\1'", sql)
    # fold '<date>' +/- interval 'n' unit  -> literal date
    while True:
        m = _INTERVAL_RE.search(s)
        if not m:
            break
        base, sign, n, unit = m.groups()
        d = np.datetime64(base, "D")
        k = int(n) if sign == "+" else -int(n)
        if unit.lower() == "day":
            d2 = d + np.timedelta64(k, "D")
        elif unit.lower() == "month":
            mm = d.astype("datetime64[M]") + np.timedelta64(k, "M")
            day = (d - d.astype("datetime64[M]")).astype(int)
            d2 = mm.astype("datetime64[D]") + np.timedelta64(int(day), "D")
        else:
            yy = d.astype("datetime64[Y]") + np.timedelta64(k, "Y")
            rest = d - d.astype("datetime64[Y]").astype("datetime64[D]")
            d2 = yy.astype("datetime64[D]") + rest
        s = s[: m.start()] + f"'{d2}'" + s[m.end():]
    s = _EXTRACT_RE.sub(r"cast(strftime('%Y', \1) as integer)", s)
    s = _SUBSTR_RE.sub(r"substr(\1, \2, \3)", s)
    return s


def run_oracle(conn: sqlite3.Connection, sql: str) -> list[tuple]:
    cur = conn.execute(to_sqlite_sql(sql))
    return [tuple(r) for r in cur.fetchall()]


def rows_match(got: list[tuple], want: list[tuple], ordered: bool,
               rtol: float = 1e-6) -> tuple[bool, str]:
    if len(got) != len(want):
        return False, f"row count {len(got)} != {len(want)}"

    def key(row):
        return tuple((x is None, str(type(x).__name__) if False else "",
                      round(x, 6) if isinstance(x, float) else x)
                     for x in row)

    g = got if ordered else sorted(got, key=key)
    w = want if ordered else sorted(want, key=key)
    for i, (gr, wr) in enumerate(zip(g, w)):
        if len(gr) != len(wr):
            return False, f"row {i} arity mismatch"
        for j, (a, b) in enumerate(zip(gr, wr)):
            if a is None or b is None:
                if a is not b:
                    return False, f"row {i} col {j}: {a!r} != {b!r}"
                continue
            if isinstance(a, float) or isinstance(b, float):
                fa, fb = float(a), float(b)
                if abs(fa - fb) > rtol * max(1.0, abs(fa), abs(fb)):
                    return False, f"row {i} col {j}: {fa} != {fb}"
                continue
            if a != b:
                return False, f"row {i} col {j}: {a!r} != {b!r}"
    return True, ""
