"""Hand-built physical plans for the TPC-H ladder (BASELINE.md stages 1-3).

These are the plans the SQL frontend will eventually emit; they exist
standalone so the engine ladder (Q6 -> Q1 -> Q14) runs before the frontend
lands, and as the benchmark kernels.  Reference execution path being
replaced: the vectorized scan-aggregate stack in SURVEY §3.3.
"""

from __future__ import annotations

from oceanbase_tpu.datatypes import SqlType
from oceanbase_tpu.exec.ops import AggSpec
from oceanbase_tpu.exec.plan import (
    Filter, GroupBy, HashJoin, Project, ScalarAgg, Sort, TableScan,
)
from oceanbase_tpu.expr import ir


def dec(s: str) -> ir.Literal:
    return ir.lit(s, SqlType.decimal())


def date(s: str) -> ir.Literal:
    return ir.lit(s, SqlType.date())


def q6_plan():
    """TPC-H Q6: SELECT sum(l_extendedprice*l_discount) AS revenue
    FROM lineitem WHERE l_shipdate >= '1994-01-01' AND l_shipdate < '1995-01-01'
    AND l_discount BETWEEN 0.05 AND 0.07 AND l_quantity < 24."""
    pred = (
        (ir.col("l_shipdate") >= date("1994-01-01"))
        .and_(ir.col("l_shipdate") < date("1995-01-01"))
        .and_(ir.col("l_discount").between(dec("0.05"), dec("0.07")))
        .and_(ir.col("l_quantity") < dec("24.00"))
    )
    scan = TableScan(
        "lineitem",
        columns=["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"],
    )
    return ScalarAgg(
        Filter(scan, pred),
        [AggSpec("revenue", "sum", ir.col("l_extendedprice") * ir.col("l_discount"))],
    )


def q1_plan():
    """TPC-H Q1: 4-group GROUP BY over lineitem with 8 aggregates."""
    disc_price = ir.col("l_extendedprice") * (dec("1.00") - ir.col("l_discount"))
    charge = disc_price * (dec("1.00") + ir.col("l_tax"))
    scan = TableScan(
        "lineitem",
        columns=[
            "l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice",
            "l_discount", "l_tax", "l_shipdate",
        ],
    )
    filt = Filter(scan, ir.col("l_shipdate") <= date("1998-09-02"))
    gb = GroupBy(
        filt,
        keys={"l_returnflag": ir.col("l_returnflag"),
              "l_linestatus": ir.col("l_linestatus")},
        aggs=[
            AggSpec("sum_qty", "sum", ir.col("l_quantity")),
            AggSpec("sum_base_price", "sum", ir.col("l_extendedprice")),
            AggSpec("sum_disc_price", "sum", disc_price),
            AggSpec("sum_charge", "sum", charge),
            AggSpec("avg_qty", "avg", ir.col("l_quantity")),
            AggSpec("avg_price", "avg", ir.col("l_extendedprice")),
            AggSpec("avg_disc", "avg", ir.col("l_discount")),
            AggSpec("count_order", "count_star"),
        ],
        out_capacity=16,
    )
    return Sort(gb, keys=[ir.col("l_returnflag"), ir.col("l_linestatus")])


def q14_plan(lineitem_rows: int):
    """TPC-H Q14: promo revenue percent over lineitem ⋈ part for one month."""
    scan_l = TableScan(
        "lineitem",
        columns=["l_partkey", "l_extendedprice", "l_discount", "l_shipdate"],
    )
    filt = Filter(
        scan_l,
        (ir.col("l_shipdate") >= date("1995-09-01"))
        .and_(ir.col("l_shipdate") < date("1995-10-01")),
    )
    scan_p = TableScan("part", columns=["p_partkey", "p_type"])
    j = HashJoin(
        filt, scan_p, [ir.col("l_partkey")], [ir.col("p_partkey")],
        how="inner", out_capacity=lineitem_rows,
    )
    disc_price = ir.col("l_extendedprice") * (dec("1.00") - ir.col("l_discount"))
    promo = ir.Case(
        whens=[(ir.col("p_type").like("PROMO%"), disc_price)],
        else_=ir.lit("0.0000", SqlType.decimal(15, 4)),
    )
    agg = ScalarAgg(j, [
        AggSpec("promo", "sum", promo),
        AggSpec("total", "sum", disc_price),
    ])
    return Project(
        agg,
        {"promo_revenue": ir.lit(100.0) * ir.col("promo") / ir.col("total")},
    )
