"""oceanbase_tpu — a TPU-native distributed HTAP SQL database framework.

A from-scratch re-design of OceanBase's capabilities (reference:
/root/reference, see SURVEY.md) with the execution plane on TPU:

- ``vector/``   columnar batch formats in HBM (analog of src/share/vector)
- ``expr/``     expression IR + JAX compiler (analog of src/sql/engine/expr)
- ``exec/``     vectorized physical operators (analog of src/sql/engine)
- ``px/``       parallel execution over a device mesh (analog of src/sql/engine/px + src/sql/dtl)
- ``sql/``      parser / resolver / rewrite / optimizer / code generator / plan cache
                (analog of src/sql/{parser,resolver,rewrite,optimizer,code_generator,plan_cache})
- ``storage/``  LSM-lite column store + memtable (analog of src/storage)
- ``tx/``       MVCC transactions, GTS, 2PC (analog of src/storage/tx)
- ``palf/``     replicated log + election (analog of src/logservice/palf)
- ``server/``   sessions, tenants, config, observability (analog of src/observer)

Control plane runs on host; the compute plane (scan/filter/agg/join/exchange)
is JAX/XLA over TPU with mesh collectives for the PX exchange.
"""

import jax

# The engine computes on exact 64-bit integers (decimals are scaled int64,
# reference: ObNumber / VEC_TC_DEC_INT* in src/share/vector/ob_vector_define.h:47-51).
# TPU emulates i64 with i32 pairs; correctness first, Pallas split kernels later.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"
