"""OBKV-style table API: key-value access bypassing the SQL compiler.

Reference analog: src/libtable + src/observer/table — a typed put/get/
delete/scan API over the same tablets and transactions as SQL, skipping
parse/resolve/optimize for point operations.
"""

from __future__ import annotations

from typing import Optional


class KvTable:
    """Point/range access to one table through the tx plane."""

    def __init__(self, tenant, table: str):
        self.tenant = tenant
        self.table = table
        self.ts = tenant.engine.tables[table]

    def _key_of(self, key) -> tuple:
        if isinstance(key, tuple):
            return key
        return (key,)

    # ------------------------------------------------------------------
    def put(self, values: dict, tx=None) -> None:
        """Insert-or-update by primary key (≙ table api INSERT_OR_UPDATE)."""
        tablet = self.ts.tablet
        full = {c: values.get(c) for c in tablet.columns
                if c != "__rowid__"}
        key = tablet.make_key(dict(values))
        # copy allocated key columns (hidden rowids) back into the stored
        # row — otherwise every keyless put persists a NULL rowid and
        # newest-wins dedup collapses all rows into one
        for kc, kv in zip(tablet.key_cols, key):
            full[kc] = kv
        svc = self.tenant.tx
        own = tx is None
        if own:
            tx = svc.begin()
        try:
            # full LSM lookup (memtables AND segments): the redo/CDC op
            # kind must reflect whether the key truly exists
            exists = self.get(key, snapshot=tx.snapshot) is not None
            svc.write(tx, self.table, tablet, key,
                      "update" if exists else "insert", full)
        except Exception:
            if own:
                svc.rollback(tx)
            raise
        if own:
            svc.commit(tx)
        self.tenant.catalog.invalidate(self.table)

    def get(self, key, columns: Optional[list] = None,
            snapshot: int | None = None, tx_id: int = 0) -> Optional[dict]:
        """Point lookup riding the index-aware LSM read path
        (storage/lookup.py): memtables newest-first, then key-sorted
        segments with zone-map chunk pruning — O(chunks-holding-key)
        decode, not a whole-segment scan.  ``tx_id`` makes the
        transaction's own uncommitted writes visible."""
        from oceanbase_tpu.storage.lookup import point_lookup

        tablet = self.ts.tablet
        key = self._key_of(key)
        snap = snapshot if snapshot is not None else \
            self.tenant.tx.gts.current()
        best = point_lookup(tablet, key, snap, tx_id)
        if best is None:
            return None
        best.pop("__rowid__", None)
        return {c: best.get(c) for c in (columns or best)}

    def delete(self, key, tx=None) -> bool:
        tablet = self.ts.tablet
        key = self._key_of(key)
        existing = self.get(key)
        if existing is None:
            return False
        svc = self.tenant.tx
        own = tx is None
        if own:
            tx = svc.begin()
        try:
            values = dict(existing)
            for kc, kv in zip(tablet.key_cols, key):
                values[kc] = kv
            svc.write(tx, self.table, tablet, key, "delete", values)
        except Exception:
            if own:
                svc.rollback(tx)
            raise
        if own:
            svc.commit(tx)
        self.tenant.catalog.invalidate(self.table)
        return True

    def scan(self, limit: int | None = None, snapshot: int | None = None):
        """Full scan returning row dicts (range scans refine later)."""
        tablet = self.ts.tablet
        snap = snapshot if snapshot is not None else \
            self.tenant.tx.gts.current()
        arrays, valids = tablet.snapshot_arrays(snap)
        n = len(next(iter(arrays.values()))) if arrays else 0
        out = []
        for i in range(n):
            if limit is not None and len(out) >= limit:
                break
            row = {}
            for c in tablet.columns:
                if c == "__rowid__":
                    continue
                vd = valids.get(c)
                x = arrays[c][i]
                row[c] = (None if vd is not None and not vd[i]
                          else x.item() if hasattr(x, "item") else x)
            out.append(row)
        return out
