"""Fused scan-filter-aggregate Pallas kernels (TPC-H Q6 shape).

The Q6 hot loop is: 3 range predicates + masked sum of a product — pure
VPU work.  The engine's generic path runs it in emulated int64 (exact
decimals); this kernel keeps the inner loop in native int32 by splitting
each product into (hi, lo) 16-bit halves and accumulating both as int32
per block — exact, and sized so no 32-bit overflow is possible:

    product = price(int32, <= ~2^27 cents) * discount(int32, <= 10)
            <= ~2^31;  hi = product >> 16 <= 2^15, lo = product & 0xFFFF
    per-block sums over BLOCK_ROWS=8192 rows:
      sum(lo) <= 8192 * 65535 < 2^29   sum(hi) <= 8192 * 2^15 = 2^28

The final reduction over per-block partials runs in int64 outside the
kernel (tiny).  ≙ the reference's SIMD white-filter + sum fusion
(ob_pushdown_filter_simd.cpp + sum_simd.h) re-imagined for the VPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK_ROWS = 8192           # 64 sublanes x 128 lanes
_SUB, _LANE = 64, 128


def _q6_kernel(ship_ref, disc_ref, qty_ref, price_ref, live_ref,
               hi_ref, lo_ref, *, ship_lo, ship_hi, disc_lo, disc_hi,
               qty_hi):
    ship = ship_ref[:]
    disc = disc_ref[:]
    qty = qty_ref[:]
    price = price_ref[:]
    live = live_ref[:]
    mask = ((ship >= ship_lo) & (ship < ship_hi)
            & (disc >= disc_lo) & (disc <= disc_hi)
            & (qty < qty_hi) & (live != 0))
    prod = price * disc * mask.astype(jnp.int32)
    hi = prod >> 16
    lo = prod & 0xFFFF
    # whole-array output block (Mosaic rejects (1,1) VMEM tiles); each
    # grid step owns one row of the partials array
    i = pl.program_id(0)
    # Reduce ONLY over sublanes in-kernel (axis 0), emitting one
    # 128-lane partial row per block; the final cross-lane reduction
    # runs outside the kernel in int64 XLA.  Two reasons, both Mosaic:
    # scalar-output reductions proxy through jnp.sum (which inserts an
    # int32->int64 convert under jax_enable_x64 that Mosaic won't
    # lower), and a lane-shaped store keeps the output VMEM-tileable.
    # reduce_sum_p is bound directly so the accumulator stays int32.
    # Bounds: sum over 64 sublanes of hi<=2^15 -> 2^21; lo<=0xFFFF ->
    # 2^22 — no int32 overflow.
    hsum = jax.lax.reduce_sum_p.bind(hi, axes=(0,))     # (128,)
    lsum = jax.lax.reduce_sum_p.bind(lo, axes=(0,))
    hi_ref[pl.dslice(i, 1), :] = hsum.reshape(1, _LANE)
    lo_ref[pl.dslice(i, 1), :] = lsum.reshape(1, _LANE)


@functools.partial(jax.jit, static_argnames=(
    "ship_lo", "ship_hi", "disc_lo", "disc_hi", "qty_hi", "interpret"))
def q6_filter_sum(shipdate, discount, quantity, extendedprice, live,
                  *, ship_lo, ship_hi, disc_lo, disc_hi, qty_hi,
                  interpret=False):
    """Exact fused Q6: sum(price * discount) over the filtered rows.

    Inputs are int32 column arrays (any length; padded internally) plus a
    live-row mask; returns the scale-4 fixed-point revenue as int64.
    """
    n = shipdate.shape[0]
    nblocks = max((n + BLOCK_ROWS - 1) // BLOCK_ROWS, 1)
    pad = nblocks * BLOCK_ROWS - n

    def prep(x, fill=0):
        x = x.astype(jnp.int32)
        if pad:
            x = jnp.concatenate(
                [x, jnp.full(pad, fill, dtype=jnp.int32)])
        return x.reshape(nblocks * _SUB, _LANE)

    ship = prep(shipdate)
    disc = prep(discount)
    qty = prep(quantity, fill=qty_hi)      # padded rows fail the filter
    price = prep(extendedprice)
    lv = prep(live.astype(jnp.int32))

    kernel = functools.partial(
        _q6_kernel, ship_lo=ship_lo, ship_hi=ship_hi,
        disc_lo=disc_lo, disc_hi=disc_hi, qty_hi=qty_hi)

    # The whole (chunk_blocks, 128) partials array stays VMEM-resident
    # for one pallas_call (the constant-index-map out spec), so bound it:
    # chunks of <= MAX_BLOCKS blocks (~1 MB of int32 partials) keep VMEM
    # flat no matter the input size; the int64 combine runs per chunk in
    # plain XLA.  (A (1,128) per-step out block would be ideal but Mosaic
    # requires the trailing block dims divisible by (8,128) or whole.)
    MAX_BLOCKS = 1024  # 8.4M rows per call
    total = jnp.zeros((), jnp.int64)
    for s in range(0, nblocks, MAX_BLOCKS):
        nb = min(MAX_BLOCKS, nblocks - s)
        rows = slice(s * _SUB, (s + nb) * _SUB)
        blk = pl.BlockSpec((_SUB, _LANE), lambda i: (i, 0),
                           memory_space=pltpu.VMEM)
        out_blk = pl.BlockSpec((nb, _LANE), lambda i: (0, 0),
                               memory_space=pltpu.VMEM)
        hi, lo = pl.pallas_call(
            kernel,
            grid=(nb,),
            in_specs=[blk, blk, blk, blk, blk],
            out_specs=(out_blk, out_blk),
            out_shape=(jax.ShapeDtypeStruct((nb, _LANE), jnp.int32),
                       jax.ShapeDtypeStruct((nb, _LANE), jnp.int32)),
            interpret=interpret,
        )(ship[rows], disc[rows], qty[rows], price[rows], lv[rows])
        total = total + (jnp.sum(hi.astype(jnp.int64)) << 16) + \
            jnp.sum(lo.astype(jnp.int64))
    return total
