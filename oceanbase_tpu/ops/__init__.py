"""Hand-written Pallas TPU kernels for the hottest scan paths.

Reference analog: the SIMD inner loops the reference hand-writes
(white-filter SIMD src/sql/engine/basic/ob_pushdown_filter_simd.cpp,
sum SIMD src/share/aggregate/sum_simd.h).  XLA already fuses most of the
engine's elementwise work; these kernels exist where exactness constraints
fight the hardware — e.g. exact decimal aggregation without emulated i64
in the inner loop (TPU is a 32-bit machine; i64 is emulated).
"""

from oceanbase_tpu.ops.scan_kernels import q6_filter_sum

__all__ = ["q6_filter_sum"]
