"""Transaction error types (≙ OB_TRY_LOCK_ROW_CONFLICT / OB_TRANS_*)."""


class WriteConflict(RuntimeError):
    """Row is write-locked by another live transaction."""


class TxAborted(RuntimeError):
    """Transaction was aborted (conflict, deadlock, or explicit rollback)."""


class DuplicateKey(WriteConflict):
    """INSERT over an existing visible primary key."""
