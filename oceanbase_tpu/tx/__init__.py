"""Transaction plane: MVCC transactions, GTS, two-phase commit, locks.

Reference analog: src/storage/tx (ObTransService ob_trans_service.h:173,
ObPartTransCtx ob_trans_part_ctx.h:148, 2PC state machine
ob_committer_define.h:61) and the GTS (ob_gts_source.h).  Host-side by
design (SURVEY north star: MVCC/tx untouched by the TPU offload).
"""

from oceanbase_tpu.tx.errors import TxAborted, WriteConflict

__all__ = ["WriteConflict", "TxAborted"]
