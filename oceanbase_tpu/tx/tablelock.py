"""Table locks + deadlock detection.

Reference analog: src/storage/tablelock (table/object locks held through
transactions) and the LCL deadlock detector (src/share/deadlock).

Locks: shared (S) / exclusive (X) table locks acquired by transactions,
released at commit/rollback.  Deadlock handling is detection-based: a
wait-for graph cycle check on every blocked acquisition (single-node, so
the reference's distributed lazy-cycle-propagation collapses to a local
DFS); the newest waiter in the cycle aborts (≙ victim selection by tx age).
"""

from __future__ import annotations

import threading
from collections import defaultdict

from oceanbase_tpu.tx.errors import TxAborted, WriteConflict


class DeadlockDetected(TxAborted):
    pass


class LockTable:
    def __init__(self):
        self._lock = threading.Condition()
        # table -> {"S": set[tx_id], "IX": set[tx_id], "X": tx_id|None}
        self._held: dict[str, dict] = defaultdict(
            lambda: {"S": set(), "IX": set(), "X": None})
        # waiter tx -> set of holder txs it waits for (wait-for graph)
        self._waits: dict[int, set] = {}

    # ------------------------------------------------------------------
    def _conflicts(self, table: str, mode: str, tx_id: int) -> set:
        """Compatibility matrix: IX~IX compatible; S~S compatible;
        S conflicts IX/X; IX conflicts S/X; X conflicts everything
        (DML takes IX implicitly; LOCK TABLES READ/WRITE take S/X)."""
        st = self._held[table]
        blockers = set()
        if st["X"] is not None and st["X"] != tx_id:
            blockers.add(st["X"])
        if mode == "S":
            blockers |= {t for t in st["IX"] if t != tx_id}
        elif mode == "IX":
            blockers |= {t for t in st["S"] if t != tx_id}
        else:  # X
            blockers |= {t for t in st["S"] if t != tx_id}
            blockers |= {t for t in st["IX"] if t != tx_id}
        return blockers

    def _would_deadlock(self, tx_id: int, blockers: set) -> bool:
        """DFS over the wait-for graph: does making tx_id wait on
        ``blockers`` close a cycle?  (≙ LCL cycle detection)"""
        stack = list(blockers)
        seen = set()
        while stack:
            t = stack.pop()
            if t == tx_id:
                return True
            if t in seen:
                continue
            seen.add(t)
            stack.extend(self._waits.get(t, ()))
        return False

    def acquire(self, table: str, mode: str, tx_id: int,
                timeout: float = 10.0):
        """Block until granted; raises DeadlockDetected on a cycle or
        WriteConflict on timeout."""
        assert mode in ("S", "X", "IX")
        with self._lock:
            deadline = None
            while True:
                blockers = self._conflicts(table, mode, tx_id)
                if not blockers:
                    st = self._held[table]
                    if mode == "S":
                        st["S"].add(tx_id)
                    elif mode == "IX":
                        st["IX"].add(tx_id)
                    else:
                        st["X"] = tx_id
                    self._waits.pop(tx_id, None)
                    return
                if self._would_deadlock(tx_id, blockers):
                    self._waits.pop(tx_id, None)
                    raise DeadlockDetected(
                        f"tx {tx_id} would deadlock on {table}")
                self._waits[tx_id] = blockers
                import time as _t

                if deadline is None:
                    deadline = _t.monotonic() + timeout
                remaining = deadline - _t.monotonic()
                if remaining <= 0:
                    self._waits.pop(tx_id, None)
                    raise WriteConflict(
                        f"lock wait timeout on {table} (tx {tx_id})")
                self._lock.wait(timeout=min(remaining, 0.5))

    def release_all(self, tx_id: int):
        with self._lock:
            for st in self._held.values():
                st["S"].discard(tx_id)
                st["IX"].discard(tx_id)
                if st["X"] == tx_id:
                    st["X"] = None
            self._waits.pop(tx_id, None)
            self._lock.notify_all()

    def holders(self, table: str) -> dict:
        with self._lock:
            st = self._held[table]
            return {"S": set(st["S"]), "X": st["X"]}
