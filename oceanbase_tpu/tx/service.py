"""Transaction service: MVCC transactions with WAL + two-phase commit.

Reference analog: ObTransService (src/storage/tx/ob_trans_service.h:173)
with per-participant ObPartTransCtx (ob_trans_part_ctx.h:148) and the
optimized 2PC state machine ObTxState INIT -> REDO_COMPLETE -> PREPARE ->
PRE_COMMIT -> COMMIT -> CLEAR (ob_committer_define.h:61-73).

Model:
- participants are tablets (the LS analog at this scale); a transaction
  collects a write set per participant.
- redo for every write is appended to the PALF log before commit
  acknowledges (WAL); commit itself is a log record.  Recovery replays the
  committed log into fresh memtables (≙ replayservice).
- single-participant commits take the one-phase fast path; multi-
  participant commits run the explicit 2PC state machine: each participant
  logs PREPARE with its local max ts; commit version = max(prepare ts)
  (≙ GTS-free prepare-version negotiation), then COMMIT records fan out.
- conflicts fail fast with WriteConflict (lock-wait queues arrive with the
  lock manager); rollback restores version chains.
"""

from __future__ import annotations

import itertools
import json
import threading
from dataclasses import dataclass, field
from enum import Enum

from oceanbase_tpu.tx.errors import TxAborted, WriteConflict
from oceanbase_tpu.tx.gts import GTS


class TxState(Enum):
    ACTIVE = "active"
    REDO_COMPLETE = "redo_complete"
    PREPARE = "prepare"
    PRE_COMMIT = "pre_commit"
    COMMIT = "commit"
    ABORT = "abort"
    CLEAR = "clear"


@dataclass
class Participant:
    """Per-tablet transaction context (≙ ObPartTransCtx)."""

    table: str
    tablet: object
    keys: list = field(default_factory=list)
    prepare_version: int = 0
    state: TxState = TxState.ACTIVE


@dataclass
class Transaction:
    tx_id: int
    snapshot: int
    state: TxState = TxState.ACTIVE
    participants: dict = field(default_factory=dict)  # table -> Participant
    stmt_seq: int = 0  # statement counter (savepoint granularity)
    # group-commit buffer: redo lives here (and in the memtable) until the
    # commit ships everything in one replicated append.  Unbounded for
    # huge transactions — incremental pre-commit flush is an r2 item.
    pending_redo: list = field(default_factory=list)

    # parallel-DML workers write under one tx concurrently; participant
    # creation must not race (keys lists are append-only, GIL-atomic)
    plock: threading.Lock = field(default_factory=threading.Lock)

    def participant(self, table: str, tablet) -> Participant:
        p = self.participants.get(table)
        if p is None:
            with self.plock:
                p = self.participants.get(table)
                if p is None:
                    p = Participant(table, tablet)
                    self.participants[table] = p
        return p


class TransService:
    """Owns the GTS, live transactions, and the WAL (a PalfCluster)."""

    def __init__(self, wal=None):
        self.gts = GTS()
        self.wal = wal            # PalfCluster or None (no replication)
        self.lock_table = None    # tx/tablelock.LockTable when attached
        self.lock_wait_timeout_s = 5.0
        # StorageEngine for secondary-index maintenance (set by the
        # tenant wiring); None disables maintenance (e.g. bare unit use)
        self.engine = None
        # unique-index rowkey locks held across duplicate checks
        # (≙ index rowkey locking; see storage/indexes.IndexKeyLocks)
        from oceanbase_tpu.storage.indexes import IndexKeyLocks

        self.index_locks = IndexKeyLocks()
        self._next_tx = itertools.count(1)
        self._live: dict[int, Transaction] = {}
        self._lock = threading.RLock()

    # ------------------------------------------------------------------
    def begin(self) -> Transaction:
        with self._lock:
            tx = Transaction(next(self._next_tx), self.gts.get_ts())
            self._live[tx.tx_id] = tx
            return tx

    def write(self, tx: Transaction, table: str, tablet, key: tuple,
              op: str, values: dict):
        if tx.state != TxState.ACTIVE:
            raise TxAborted(f"tx {tx.tx_id} is {tx.state.value}")
        if self.lock_table is not None:
            # implicit intent-exclusive table lock: honors LOCK TABLES
            # READ/WRITE held by other transactions (released at tx end)
            self.lock_table.acquire(table, "IX", tx.tx_id,
                                    timeout=self.lock_wait_timeout_s)
        if self.engine is not None:
            # secondary indexes update in the SAME transaction, before
            # the base write (pre-image must still be the old row);
            # recursive svc.write calls give index entries WAL redo,
            # statement rollback, and replay for free
            from oceanbase_tpu.storage.indexes import maintain_indexes

            maintain_indexes(self, self.engine, tx, table, tablet, key,
                             op, values)
        tablet.write(key, op, values, tx.tx_id, stmt_seq=tx.stmt_seq,
                     snapshot=tx.snapshot)
        p = tx.participant(table, tablet)
        p.keys.append(key)
        # redo buffers in the tx and ships in ONE replicated group append
        # at commit (≙ the sliding window's group buffer batching —
        # N writes cost one majority fsync, not N)
        tx.pending_redo.append(
            {"op": "redo", "tx": tx.tx_id, "table": table,
             "key": list(key), "kind": op, "stmt": tx.stmt_seq,
             "values": _jsonable(values)})

    def rollback_statement(self, tx: Transaction, stmt_seq: int,
                           stmt_writes: dict):
        """Undo a failed statement's writes inside a live transaction
        (statement-level atomicity, ≙ savepoint rollback).
        stmt_writes: table -> list of keys written by the statement."""
        for table, keys in stmt_writes.items():
            p = tx.participants.get(table)
            if p is None:
                continue
            p.tablet.abort(tx.tx_id, keys, min_stmt_seq=stmt_seq)
            # p.keys keeps earlier-statement entries; commit() tolerates
            # keys whose uncommitted versions were statement-aborted
        # drop the statement's buffered redo (it never hit the WAL)
        tx.pending_redo = [r for r in tx.pending_redo
                           if r.get("stmt", 0) < stmt_seq]
        # index rowkey locks the statement introduced go with it — a
        # rolled-back INSERT must not wedge its unique value until tx end
        self.index_locks.release_stmt(tx.tx_id, stmt_seq)

    # ------------------------------------------------------------------
    def commit(self, tx: Transaction) -> int:
        """One-phase fast path or full 2PC; returns the commit version."""
        from oceanbase_tpu.server.errsim import ERRSIM

        ERRSIM.hit("tx.commit")
        with self._lock:
            if tx.state != TxState.ACTIVE:
                raise TxAborted(f"tx {tx.tx_id} is {tx.state.value}")
            parts = list(tx.participants.values())
            if not parts:
                tx.state = TxState.CLEAR
                self._live.pop(tx.tx_id, None)
                self._release_locks(tx)
                return self.gts.get_ts()
            if len(parts) == 1:
                # single-LS fast path (≙ one-phase commit optimization):
                # buffered redo + commit ship as one group append
                version = self.gts.get_ts()
                self._log_batch(tx.pending_redo +
                                [{"op": "commit", "tx": tx.tx_id,
                                  "version": version}])
                tx.pending_redo = []
                parts[0].tablet.commit(tx.tx_id, version, parts[0].keys)
                tx.state = TxState.CLEAR
                self._live.pop(tx.tx_id, None)
                self._release_locks(tx)
                return version

            # ---- 2PC (≙ upstream/downstream committer state machine) ----
            tx.state = TxState.REDO_COMPLETE
            records = list(tx.pending_redo)
            for p in parts:
                p.state = TxState.PREPARE
                p.prepare_version = self.gts.get_ts()
                records.append({"op": "prepare", "tx": tx.tx_id,
                                "table": p.table,
                                "version": p.prepare_version})
            version = max(p.prepare_version for p in parts)
            tx.state = TxState.PRE_COMMIT
            records.append({"op": "commit", "tx": tx.tx_id,
                            "version": version})
            self._log_batch(records)
            tx.pending_redo = []
            tx.state = TxState.COMMIT
            for p in parts:
                p.tablet.commit(tx.tx_id, version, p.keys)
                p.state = TxState.COMMIT
            tx.state = TxState.CLEAR
            self._live.pop(tx.tx_id, None)
            self._release_locks(tx)
            return version

    # ------------------------------------------------------------------
    # XA: externally-coordinated two-phase commit (≙ ObXAService,
    # src/storage/tx/ob_xa_service.h — the prepare/commit phases split
    # across statements, possibly across sessions)
    # ------------------------------------------------------------------
    def xa_prepare(self, tx: Transaction):
        """Phase 1: make the tx's redo + prepare records durable; the tx
        stays in PREPARE until an explicit XA COMMIT/ROLLBACK.

        LIMITATION (round 5): the PREPARE state itself is process-local —
        replay does not yet reconstruct prepared txs after a restart, so
        a crash between PREPARE and COMMIT implicitly rolls the branch
        back (its redo is buffered but never applied without a commit
        record).  The reference recovers into prepared state
        (ob_xa_service.h); the WAL already carries the records needed."""
        with self._lock:
            if tx.state != TxState.ACTIVE:
                raise TxAborted(f"tx {tx.tx_id} is {tx.state.value}")
            records = list(tx.pending_redo)
            for p in tx.participants.values():
                p.state = TxState.PREPARE
                p.prepare_version = self.gts.get_ts()
                records.append({"op": "prepare", "tx": tx.tx_id,
                                "table": p.table,
                                "version": p.prepare_version})
            self._log_batch(records)
            tx.pending_redo = []
            tx.state = TxState.PREPARE

    def xa_commit_prepared(self, tx: Transaction) -> int:
        """Phase 2 commit of a PREPARED tx (any session may drive it)."""
        with self._lock:
            if tx.state != TxState.PREPARE:
                raise TxAborted(
                    f"tx {tx.tx_id} is {tx.state.value}, not prepared")
            parts = list(tx.participants.values())
            version = max((p.prepare_version for p in parts),
                          default=self.gts.get_ts())
            self._log({"op": "commit", "tx": tx.tx_id,
                       "version": version})
            for p in parts:
                p.tablet.commit(tx.tx_id, version, p.keys)
                p.state = TxState.COMMIT
            tx.state = TxState.CLEAR
            self._live.pop(tx.tx_id, None)
            self._release_locks(tx)
            return version

    def xa_rollback_prepared(self, tx: Transaction):
        with self._lock:
            if tx.state != TxState.PREPARE:
                return self.rollback(tx)
            # redo already reached the WAL at prepare: log the abort so
            # replay drops the buffered records
            self._log({"op": "abort", "tx": tx.tx_id})
            for p in tx.participants.values():
                p.tablet.abort(tx.tx_id, p.keys)
            tx.state = TxState.ABORT
            self._live.pop(tx.tx_id, None)
            self._release_locks(tx)

    def rollback(self, tx: Transaction):
        with self._lock:
            if tx.state == TxState.CLEAR:
                return
            for p in tx.participants.values():
                p.tablet.abort(tx.tx_id, p.keys)
            # redo never reached the WAL (group commit): nothing to log
            tx.pending_redo = []
            tx.state = TxState.ABORT
            self._live.pop(tx.tx_id, None)
            self._release_locks(tx)

    # ------------------------------------------------------------------
    def _release_locks(self, tx: Transaction):
        self.index_locks.release_all(tx.tx_id)
        if self.lock_table is not None:
            self.lock_table.release_all(tx.tx_id)

    def _log(self, record: dict) -> int:
        if self.wal is not None:
            return self.wal.append([json.dumps(record).encode()])
        return 0

    def _log_batch(self, records: list) -> int:
        """Group append: one majority-replicated fsync for the whole
        batch (≙ LogSlidingWindow group buffer)."""
        if self.wal is not None and records:
            return self.wal.append(
                [json.dumps(r).encode() for r in records])
        return 0

    # NOTE: with group commit, a live transaction has NO presence in the
    # WAL (redo ships atomically with its commit record), so checkpoints
    # no longer need a replay-point barrier at the oldest live tx — the
    # pre-group-commit min_active_wal_lsn clamp was removed with it.

    # ------------------------------------------------------------------
    # recovery (≙ replayservice applying committed log to memtables)
    # ------------------------------------------------------------------
    @staticmethod
    def replay(entries, engine, pending: dict | None = None):
        """Replay committed WAL records into a StorageEngine's memtables.
        Redo is buffered per tx and applied at its commit record, matching
        commit-version visibility.  ``pending`` carries the redo buffer
        across incremental calls (follower apply streams one entry at a
        time, ≙ replayservice applying as committed_lsn advances)."""
        if pending is None:
            pending = {}
        max_ts = 0
        for e in entries:
            try:
                rec = json.loads(e.payload.decode())
            except Exception:
                continue
            op = rec.get("op")
            if op == "ddl":
                # replicated logical DDL (multi-node log stream).  Apply
                # idempotently vs slog-applied state: the originator's
                # own slog may already hold the op (boot replays slog
                # first, then the WAL suffix).
                _replay_ddl(rec["slog"], engine)
            elif op == "redo":
                pending.setdefault(rec["tx"], []).append(rec)
            elif op == "commit":
                version = rec["version"]
                max_ts = max(max_ts, version)
                for r in pending.pop(rec["tx"], []):
                    ts = engine.tables.get(r["table"])
                    if ts is None:
                        continue
                    key = tuple(r["key"])
                    ts.tablet.write(key, r["kind"], r["values"], rec["tx"])
                    ts.tablet.commit(rec["tx"], version, [key])
            elif op == "abort":
                # only pre-group-commit WALs contain abort records; kept
                # for replaying logs written by older versions
                pending.pop(rec["tx"], None)
            elif op == "truncate":
                # replayed in log order: discard everything replayed into
                # the table so far (≙ TRUNCATE barrier in the redo stream).
                # Secondary-index storage tables truncate with their base:
                # their redo replays alongside the base rows, so the
                # barrier must clear them identically or recovered index
                # entries would resurrect pre-truncate values.
                table = rec["table"]
                targets = [table]
                base = engine.tables.get(table)
                if base is not None:
                    targets += [ix.storage_table
                                for ix in base.tdef.indexes]
                for t in targets:
                    if e.lsn <= engine.truncate_barriers.get(t, 0):
                        # the slog already applied this truncate AND
                        # restored post-truncate direct-load segments;
                        # only clear what WAL replay put into memtables
                        engine.reset_memtables(t)
                    elif t in engine.tables:
                        engine.truncate_table(t, log=False)
                # drop buffered redo of the table (writers finish before
                # the barrier thanks to the X table lock; belt-and-braces)
                tset = set(targets)
                for recs in pending.values():
                    recs[:] = [r for r in recs if r["table"] not in tset]
        return max_ts


def _replay_ddl(op: dict, engine):
    """Apply one replicated DDL op, skipping anything the engine's own
    slog already applied (create/drop/alter become no-ops when the
    target state is already present — WAL DDL replay must never wipe
    slog-restored segments, e.g. a CTAS bulk load with no redo)."""
    kind = op.get("op")
    if kind in ("create_table", "drop_table"):
        exists = op.get("name") in engine.tables
        if (kind == "create_table" and exists) or \
                (kind == "drop_table" and not exists):
            return
    elif kind in ("alter_add", "alter_drop"):
        ts = engine.tables.get(op.get("table"))
        if ts is not None:
            cname = (op["column"][0] if kind == "alter_add"
                     else op.get("column"))
            has = any(c.name == cname for c in ts.tdef.columns)
            if (kind == "alter_add" and has) or \
                    (kind == "alter_drop" and not has):
                return
    # create_index/drop_index/truncate: engine._replay is idempotent
    engine._replay(op)


def _jsonable(values: dict) -> dict:
    out = {}
    for k, v in values.items():
        if hasattr(v, "item"):
            v = v.item()
        out[k] = v
    return out
