"""Transaction service: MVCC transactions with WAL + two-phase commit.

Reference analog: ObTransService (src/storage/tx/ob_trans_service.h:173)
with per-participant ObPartTransCtx (ob_trans_part_ctx.h:148) and the
optimized 2PC state machine ObTxState INIT -> REDO_COMPLETE -> PREPARE ->
PRE_COMMIT -> COMMIT -> CLEAR (ob_committer_define.h:61-73).

Model:
- participants are tablets (the LS analog at this scale); a transaction
  collects a write set per participant.
- redo for every write is appended to the PALF log before commit
  acknowledges (WAL); commit itself is a log record.  Recovery replays the
  committed log into fresh memtables (≙ replayservice).
- single-participant commits take the one-phase fast path; multi-
  participant commits run the explicit 2PC state machine: each participant
  logs PREPARE with its local max ts; commit version = max(prepare ts)
  (≙ GTS-free prepare-version negotiation), then COMMIT records fan out.
- conflicts fail fast with WriteConflict (lock-wait queues arrive with the
  lock manager); rollback restores version chains.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from enum import Enum

from oceanbase_tpu.tx.errors import TxAborted, WriteConflict
from oceanbase_tpu.tx.gts import GTS


class TxState(Enum):
    ACTIVE = "active"
    REDO_COMPLETE = "redo_complete"
    PREPARE = "prepare"
    PRE_COMMIT = "pre_commit"
    COMMIT = "commit"
    ABORT = "abort"
    CLEAR = "clear"


@dataclass
class Participant:
    """Per-tablet transaction context (≙ ObPartTransCtx)."""

    table: str
    tablet: object
    keys: list = field(default_factory=list)
    prepare_version: int = 0
    state: TxState = TxState.ACTIVE


@dataclass
class Transaction:
    tx_id: int
    snapshot: int
    state: TxState = TxState.ACTIVE
    participants: dict = field(default_factory=dict)  # table -> Participant
    stmt_seq: int = 0  # statement counter (savepoint granularity)
    # XA: external branch id (set by the session on XA START) and, after
    # XA PREPARE, the WAL replay point that must stay BELOW any
    # checkpoint while this branch is pending (its redo lives only in
    # the WAL until commit)
    xid: str | None = None
    prepare_lsn: int = -1  # -1: no WAL presence to protect
    # crash recovery: marks a branch reconstructed from replayed
    # prepare records (sync_recovered re-creates its uncommitted
    # tablet versions, so commit/rollback take the ordinary paths)
    recovered: bool = False
    # WAL commit point when this tx began: commits at/below it are
    # strictly older than this tx's snapshot (commit serializes under
    # the service lock), so a checkpoint replay point clamped to the
    # oldest live begin_lsn only covers commits its clamped flush
    # snapshot captured
    begin_lsn: int = 0
    # group-commit buffer: redo lives here (and in the memtable) until the
    # commit ships everything in one replicated append.  Unbounded for
    # huge transactions — incremental pre-commit flush is an r2 item.
    pending_redo: list = field(default_factory=list)

    # parallel-DML workers write under one tx concurrently; participant
    # creation must not race (keys lists are append-only, GIL-atomic)
    plock: threading.Lock = field(default_factory=threading.Lock)

    def participant(self, table: str, tablet) -> Participant:
        p = self.participants.get(table)
        if p is None:
            with self.plock:
                p = self.participants.get(table)
                if p is None:
                    p = Participant(table, tablet)
                    self.participants[table] = p
        return p


class TransService:
    """Owns the GTS, live transactions, and the WAL (a PalfCluster)."""

    def __init__(self, wal=None):
        self.gts = GTS()
        self.wal = wal            # PalfCluster or None (no replication)
        self.lock_table = None    # tx/tablelock.LockTable when attached
        self.lock_wait_timeout_s = 5.0
        # memstore write backpressure (server/admission.py::
        # MemstoreThrottle, wired by the tenant): write() is the one
        # choke point every writer crosses — session DML, PDML workers,
        # OBKV — so accounting and the ramp/hard-limit gate live here;
        # None disables (bare unit use, WAL replay writes bypass write())
        self.throttle = None
        # disk-pressure plane (server/diskmgr.DiskManager, wired by the
        # tenant): the same choke point fails writes fast with typed
        # TenantReadOnly while a disk budget is exhausted; None disables
        self.diskmgr = None
        # StorageEngine for secondary-index maintenance (set by the
        # tenant wiring); None disables maintenance (e.g. bare unit use)
        self.engine = None
        # unique-index rowkey locks held across duplicate checks
        # (≙ index rowkey locking; see storage/indexes.IndexKeyLocks)
        from oceanbase_tpu.storage.indexes import IndexKeyLocks

        self.index_locks = IndexKeyLocks()
        self._next_tx_id = 0
        self._live: dict[int, Transaction] = {}
        self._lock = threading.RLock()
        # XA branch registry: xid -> Transaction (live-prepared or
        # crash-recovered); the session's XA verbs drive it
        self.xa_transactions: dict[str, Transaction] = {}
        # WAL replay state, shared between boot replay and incremental
        # follower apply so a commit record arriving AFTER a restart
        # still finds the redo the boot replay buffered:
        #   replay_pending:  tx -> [redo records] not yet committed
        #   replay_prepared: tx -> {xid, version, lsn, tables} of
        #                    prepare records with no commit/abort yet
        self.replay_pending: dict[int, list] = {}
        self.replay_prepared: dict[int, dict] = {}

    # ------------------------------------------------------------------
    def advance_tx_id(self, past: int):
        """Never-go-back seeding on recovery: replayed transactions keep
        their ids; new ones must not collide with a reconstructed
        prepared branch's uncommitted id space."""
        with self._lock:
            self._next_tx_id = max(self._next_tx_id, int(past))

    def begin(self) -> Transaction:
        with self._lock:
            self._next_tx_id += 1
            tx = Transaction(self._next_tx_id, self.gts.get_ts())
            if self.wal is not None:
                tx.begin_lsn = self.wal.committed_lsn()
            self._live[tx.tx_id] = tx
            return tx

    def flush_horizon(self):
        """-> (snapshot, wal_lsn) safe for a memtable flush/checkpoint,
        clamped to the oldest ACTIVE transaction.

        First-committer-wins reads version CHAINS: a version committed
        after a live writer's snapshot must stay in the memtables
        (mini_compact carries post-snapshot versions back into the
        active memtable) or the conflict becomes invisible once flushed
        into a segment — a lost update.  The wal_lsn half keeps the
        checkpoint replay point consistent with the clamped snapshot:
        commits at/below the oldest live begin_lsn are strictly older
        than every live snapshot, hence covered by the flush."""
        with self._lock:
            active = [t for t in self._live.values()
                      if t.state == TxState.ACTIVE]
            snap = min([self.gts.current()]
                       + [t.snapshot for t in active])
            lsn = 0 if self.wal is None else \
                min([self.wal.committed_lsn()]
                    + [t.begin_lsn for t in active])
            return snap, lsn

    def flush_snapshot(self) -> int:
        return self.flush_horizon()[0]

    def write(self, tx: Transaction, table: str, tablet, key: tuple,
              op: str, values: dict):
        if tx.state != TxState.ACTIVE:
            raise TxAborted(f"tx {tx.tx_id} is {tx.state.value}")
        if self.diskmgr is not None and not table.startswith("__idx__"):
            # read-only degradation gate: fails fast (typed
            # TenantReadOnly) while a disk budget is exhausted — reads
            # never cross this point, so they keep serving
            self.diskmgr.admit_write()
        if self.throttle is not None and not table.startswith("__idx__"):
            # BEFORE the append: ramped sleep past the trigger, typed
            # MemstoreFull at the hard limit (index maintenance rides
            # its base write's admission — accounting would double)
            self.throttle.admit_write(table, values)
        if self.lock_table is not None:
            # implicit intent-exclusive table lock: honors LOCK TABLES
            # READ/WRITE held by other transactions (released at tx end)
            self.lock_table.acquire(table, "IX", tx.tx_id,
                                    timeout=self.lock_wait_timeout_s)
        if self.engine is not None:
            # secondary indexes update in the SAME transaction, before
            # the base write (pre-image must still be the old row);
            # recursive svc.write calls give index entries WAL redo,
            # statement rollback, and replay for free
            from oceanbase_tpu.storage.indexes import maintain_indexes

            maintain_indexes(self, self.engine, tx, table, tablet, key,
                             op, values)
        tablet.write(key, op, values, tx.tx_id, stmt_seq=tx.stmt_seq,
                     snapshot=tx.snapshot)
        p = tx.participant(table, tablet)
        p.keys.append(key)
        # redo buffers in the tx and ships in ONE replicated group append
        # at commit (≙ the sliding window's group buffer batching —
        # N writes cost one majority fsync, not N)
        tx.pending_redo.append(
            {"op": "redo", "tx": tx.tx_id, "table": table,
             "key": list(key), "kind": op, "stmt": tx.stmt_seq,
             "values": _jsonable(values)})

    def rollback_statement(self, tx: Transaction, stmt_seq: int,
                           stmt_writes: dict):
        """Undo a failed statement's writes inside a live transaction
        (statement-level atomicity, ≙ savepoint rollback).
        stmt_writes: table -> list of keys written by the statement."""
        for table, keys in stmt_writes.items():
            p = tx.participants.get(table)
            if p is None:
                continue
            p.tablet.abort(tx.tx_id, keys, min_stmt_seq=stmt_seq)
            # p.keys keeps earlier-statement entries; commit() tolerates
            # keys whose uncommitted versions were statement-aborted
        # drop the statement's buffered redo (it never hit the WAL)
        tx.pending_redo = [r for r in tx.pending_redo
                           if r.get("stmt", 0) < stmt_seq]
        # index rowkey locks the statement introduced go with it — a
        # rolled-back INSERT must not wedge its unique value until tx end
        self.index_locks.release_stmt(tx.tx_id, stmt_seq)

    # ------------------------------------------------------------------
    def commit(self, tx: Transaction) -> int:
        """One-phase fast path or full 2PC; returns the commit version."""
        from oceanbase_tpu.server.errsim import ERRSIM

        ERRSIM.hit("tx.commit")
        with self._lock:
            if tx.state != TxState.ACTIVE:
                raise TxAborted(f"tx {tx.tx_id} is {tx.state.value}")
            parts = list(tx.participants.values())
            if not parts:
                tx.state = TxState.CLEAR
                self._live.pop(tx.tx_id, None)
                self._release_locks(tx)
                return self.gts.get_ts()
            if len(parts) == 1:
                # single-LS fast path (≙ one-phase commit optimization):
                # buffered redo + commit ship as one group append
                version = self.gts.get_ts()
                self._log_batch(tx.pending_redo +
                                [{"op": "commit", "tx": tx.tx_id,
                                  "version": version}])
                tx.pending_redo = []
                parts[0].tablet.commit(tx.tx_id, version, parts[0].keys)
                tx.state = TxState.CLEAR
                self._live.pop(tx.tx_id, None)
                self._release_locks(tx)
                return version

            # ---- 2PC (≙ upstream/downstream committer state machine) ----
            tx.state = TxState.REDO_COMPLETE
            records = list(tx.pending_redo)
            for p in parts:
                p.state = TxState.PREPARE
                p.prepare_version = self.gts.get_ts()
                records.append({"op": "prepare", "tx": tx.tx_id,
                                "table": p.table,
                                "version": p.prepare_version})
            version = max(p.prepare_version for p in parts)
            tx.state = TxState.PRE_COMMIT
            records.append({"op": "commit", "tx": tx.tx_id,
                            "version": version})
            self._log_batch(records)
            tx.pending_redo = []
            tx.state = TxState.COMMIT
            for p in parts:
                p.tablet.commit(tx.tx_id, version, p.keys)
                p.state = TxState.COMMIT
            tx.state = TxState.CLEAR
            self._live.pop(tx.tx_id, None)
            self._release_locks(tx)
            return version

    # ------------------------------------------------------------------
    # XA: externally-coordinated two-phase commit (≙ ObXAService,
    # src/storage/tx/ob_xa_service.h — the prepare/commit phases split
    # across statements, possibly across sessions)
    # ------------------------------------------------------------------
    def xa_prepare(self, tx: Transaction):
        """Phase 1: make the tx's redo + prepare records durable; the tx
        stays in PREPARE until an explicit XA COMMIT/ROLLBACK.

        Durability: the prepare records carry the branch xid, so a crash
        between PREPARE and COMMIT reconstructs the branch at replay
        (``restore_prepared``) instead of implicitly rolling it back —
        ≙ ObXAService recovering into prepared state
        (src/storage/tx/ob_xa_service.h).  ``tx.prepare_lsn`` records
        the WAL replay point that checkpoints must not advance past
        while the branch is pending (its redo exists ONLY in the WAL)."""
        with self._lock:
            if tx.state != TxState.ACTIVE:
                raise TxAborted(f"tx {tx.tx_id} is {tx.state.value}")
            records = list(tx.pending_redo)
            for p in tx.participants.values():
                p.state = TxState.PREPARE
                p.prepare_version = self.gts.get_ts()
                records.append({"op": "prepare", "tx": tx.tx_id,
                                "table": p.table, "xid": tx.xid,
                                "version": p.prepare_version})
            end_lsn = self._log_batch(records)
            # the batch occupies [end-len+1, end]: a checkpoint replay
            # point at end-len still replays every record of the batch
            # (an empty or WAL-less branch has nothing to protect)
            if records and end_lsn:
                tx.prepare_lsn = max(end_lsn - len(records), 0)
            tx.pending_redo = []
            tx.state = TxState.PREPARE
            if tx.xid is not None:
                self.xa_transactions[tx.xid] = tx

    def xa_commit_prepared(self, tx: Transaction) -> int:
        """Phase 2 commit of a PREPARED tx (any session may drive it) —
        crash-recovered branches included (sync_recovered restored
        their uncommitted tablet versions, so this is one code path)."""
        with self._lock:
            if tx.state != TxState.PREPARE:
                raise TxAborted(
                    f"tx {tx.tx_id} is {tx.state.value}, not prepared")
            # a crash-recovered branch took the live shape at
            # sync_recovered (uncommitted tablet versions + participants),
            # so one path commits both — and the commit version is the
            # negotiated prepare version either way, keeping the WAL
            # record identical to what followers will stamp
            parts = list(tx.participants.values())
            version = max((p.prepare_version for p in parts),
                          default=self.gts.get_ts())
            self._log({"op": "commit", "tx": tx.tx_id,
                       "version": version})
            for p in parts:
                if p.tablet is not None:
                    p.tablet.commit(tx.tx_id, version, p.keys)
                p.state = TxState.COMMIT
            self.gts.advance_to(version)
            tx.state = TxState.CLEAR
            self._forget_xa_locked(tx)
            self._release_locks(tx)
            return version

    def xa_rollback_prepared(self, tx: Transaction):
        with self._lock:
            if tx.state != TxState.PREPARE:
                return self.rollback(tx)
            # redo already reached the WAL at prepare: log the abort so
            # replay drops the buffered records
            self._log({"op": "abort", "tx": tx.tx_id})
            for p in tx.participants.values():
                if p.tablet is not None:
                    p.tablet.abort(tx.tx_id, p.keys)
            tx.state = TxState.ABORT
            self._forget_xa_locked(tx)
            self._release_locks(tx)

    def _forget_xa_locked(self, tx: Transaction):
        """Drop every trace of a terminated XA branch: the live map, the
        xid registry, and the replay buffers (so an ended branch stops
        clamping checkpoints and cannot be re-registered by sync)."""
        self._live.pop(tx.tx_id, None)
        if tx.xid is not None:
            cur = self.xa_transactions.get(tx.xid)
            if cur is tx:
                self.xa_transactions.pop(tx.xid, None)
        self.replay_pending.pop(tx.tx_id, None)
        self.replay_prepared.pop(tx.tx_id, None)

    def recoverable_xids(self) -> list[str]:
        """XA RECOVER's data: xids of branches in PREPARE state (live or
        crash-reconstructed) this service can still commit or roll back."""
        with self._lock:
            return sorted(x for x, tx in self.xa_transactions.items()
                          if tx.state == TxState.PREPARE)

    def min_prepared_lsn(self):
        """Smallest WAL replay point still needed by a pending prepared
        branch (live or recovered), or None.  Checkpoints clamp their
        replay point to it: a prepared branch's redo lives ONLY in the
        WAL, so advancing past its prepare batch would lose the branch
        at the next restart."""
        with self._lock:
            lsns = [tx.prepare_lsn for tx in self._live.values()
                    if tx.state == TxState.PREPARE
                    and tx.xid is not None and tx.prepare_lsn >= 0]
            return min(lsns) if lsns else None

    def rollback(self, tx: Transaction):
        with self._lock:
            if tx.state == TxState.CLEAR:
                return
            for p in tx.participants.values():
                p.tablet.abort(tx.tx_id, p.keys)
            # redo never reached the WAL (group commit): nothing to log
            tx.pending_redo = []
            tx.state = TxState.ABORT
            self._live.pop(tx.tx_id, None)
            self._release_locks(tx)

    # ------------------------------------------------------------------
    def _release_locks(self, tx: Transaction):
        self.index_locks.release_all(tx.tx_id)
        if self.lock_table is not None:
            self.lock_table.release_all(tx.tx_id)

    def _log(self, record: dict) -> int:
        if self.wal is not None:
            return self.wal.append([json.dumps(record).encode()])
        return 0

    def _log_batch(self, records: list) -> int:
        """Group append: one majority-replicated fsync for the whole
        batch (≙ LogSlidingWindow group buffer)."""
        if self.wal is not None and records:
            return self.wal.append(
                [json.dumps(r).encode() for r in records])
        return 0

    # NOTE: with group commit, a live transaction has NO presence in the
    # WAL (redo ships atomically with its commit record), so checkpoints
    # no longer need a replay-point barrier at the oldest live tx — the
    # pre-group-commit min_active_wal_lsn clamp was removed with it.

    # ------------------------------------------------------------------
    # recovery (≙ replayservice applying committed log to memtables)
    # ------------------------------------------------------------------
    def apply_replay(self, entries, stats: dict | None = None) -> int:
        """Instance replay against this service's persistent replay
        buffers: boot replay and incremental follower apply share ONE
        pending/prepared state, so a commit record that arrives through
        catch-up AFTER a restart still finds the redo the boot replay
        buffered.  Keeps the xid registry in sync (prepared branches
        appear in XA RECOVER as soon as their prepare record applies;
        terminated ones disappear) and returns the max commit ts seen."""
        if stats is None:
            stats = {}
        max_ts = self.replay(entries, self.engine,
                             pending=self.replay_pending,
                             prepared=self.replay_prepared, stats=stats)
        self.sync_recovered()
        # seed the tx-id allocator past every replayed id: a follower
        # promoted to leader must not mint ids that collide with a
        # replayed (possibly still-prepared) transaction's id space
        self.advance_tx_id(stats.get("max_tx", 0))
        return max_ts

    def restore_prepared(self) -> list:
        """Boot-time hook (after the WAL tail replays): reconstruct every
        XA branch whose prepare records survived with no commit/abort —
        ≙ ObXAService crash recovery into prepared state.  Returns ALL
        currently-recovered branches (incremental replay may have
        registered them already), also reachable via XA RECOVER."""
        self.sync_recovered()
        with self._lock:
            return [tx for tx in self._live.values()
                    if tx.recovered and tx.state == TxState.PREPARE]

    def sync_recovered(self) -> list:
        """Reconcile the xid registry with the replay buffers: register
        newly-replayed prepared branches, drop branches a replayed
        commit/abort record terminated.

        A reconstructed branch takes the LIVE prepared shape: its redo
        is re-written into the tablets as UNCOMMITTED versions, so
        first-committer-wins checks see the branch exactly like before
        the crash (a concurrent write to its keys conflicts instead of
        silently racing the pending XA COMMIT), and the commit/rollback
        paths are the ordinary participant paths.  (Unique-index ROWKEY
        locks are not reacquired — narrower than the reference's
        recovered lock tables.)"""
        restored = []
        with self._lock:
            for tx_id, info in sorted(self.replay_prepared.items()):
                xid = info.get("xid")
                if xid is None or tx_id in self._live:
                    continue  # pre-durable-XA record or already known
                redo = list(self.replay_pending.get(tx_id, []))
                version = int(info.get("version", 0))
                tx = Transaction(tx_id, snapshot=version)
                tx.state = TxState.PREPARE
                tx.xid = xid
                tx.recovered = True
                # the replay point that still covers the whole batch is
                # one below its first record
                first = min([int(info.get("lsn", 1))]
                            + [int(r.get("_lsn", 1)) for r in redo])
                tx.prepare_lsn = max(first - 1, 0)
                for r in redo:
                    ts = (self.engine.tables.get(r["table"])
                          if self.engine is not None else None)
                    p = tx.participant(
                        r["table"], ts.tablet if ts is not None else None)
                    key = tuple(r["key"])
                    p.keys.append(key)
                    p.state = TxState.PREPARE
                    p.prepare_version = version
                    if ts is not None:
                        # no snapshot arg: recovery reapply, the check
                        # that would conflict is the one being restored
                        ts.tablet.write(key, r["kind"], r["values"],
                                        tx_id)
                self._live[tx_id] = tx
                self.xa_transactions[xid] = tx
                self.advance_tx_id(tx_id)
                self.gts.advance_to(version)
                restored.append(tx)
            # a commit/abort record replayed for a branch we had
            # reconstructed: replay already applied (or dropped) its
            # redo — retire the placeholder.  After a replayed COMMIT
            # the reconstructed versions were stamped alongside the
            # pending redo (same tx id), so the abort below is a no-op;
            # after a replayed ABORT it removes them.
            for tx_id in [t for t, tx in self._live.items()
                          if tx.recovered
                          and t not in self.replay_prepared]:
                tx = self._live.pop(tx_id)
                for p in tx.participants.values():
                    if p.tablet is not None:
                        p.tablet.abort(tx_id, p.keys)
                if tx.xid is not None and \
                        self.xa_transactions.get(tx.xid) is tx:
                    self.xa_transactions.pop(tx.xid, None)
        return restored

    @staticmethod
    def replay(entries, engine, pending: dict | None = None,
               prepared: dict | None = None, stats: dict | None = None):
        """Replay committed WAL records into a StorageEngine's memtables.
        Redo is buffered per tx and applied at its commit record, matching
        commit-version visibility.  ``pending`` carries the redo buffer
        across incremental calls (follower apply streams one entry at a
        time, ≙ replayservice applying as committed_lsn advances);
        ``prepared`` (optional) collects prepare records not yet
        terminated by a commit/abort — the durable-XA reconstruction
        input; ``stats`` (optional) accumulates replay progress counters
        for gv$recovery."""
        if pending is None:
            pending = {}
        if stats is None:
            stats = {}
        max_ts = 0
        for e in entries:
            stats["entries"] = stats.get("entries", 0) + 1
            try:
                rec = json.loads(e.payload.decode())
            except Exception:
                continue
            tx_id = rec.get("tx")
            if tx_id is not None:
                stats["max_tx"] = max(stats.get("max_tx", 0), tx_id)
            op = rec.get("op")
            if op == "ddl":
                # replicated logical DDL (multi-node log stream).  Apply
                # idempotently vs slog-applied state: the originator's
                # own slog may already hold the op (boot replays slog
                # first, then the WAL suffix).
                _replay_ddl(rec["slog"], engine)
            elif op == "redo":
                rec["_lsn"] = e.lsn  # prepared-branch replay-point bound
                pending.setdefault(rec["tx"], []).append(rec)
            elif op == "prepare":
                # XA phase 1 (durable): remember the branch until a
                # commit/abort terminates it; leftovers at the end of
                # replay are crash-recoverable prepared branches
                if prepared is not None:
                    info = prepared.setdefault(rec["tx"], {})
                    if rec.get("xid") is not None:
                        info["xid"] = rec["xid"]
                    info["version"] = max(int(info.get("version", 0)),
                                          int(rec.get("version", 0)))
                    info["lsn"] = min(int(info.get("lsn", e.lsn)), e.lsn)
                    stats["prepared"] = stats.get("prepared", 0) + 1
            elif op == "commit":
                version = rec["version"]
                max_ts = max(max_ts, version)
                stats["commits"] = stats.get("commits", 0) + 1
                for r in pending.pop(rec["tx"], []):
                    ts = engine.tables.get(r["table"])
                    if ts is None:
                        continue
                    key = tuple(r["key"])
                    ts.tablet.write(key, r["kind"], r["values"], rec["tx"])
                    ts.tablet.commit(rec["tx"], version, [key])
                if prepared is not None:
                    prepared.pop(rec["tx"], None)
            elif op == "abort":
                # XA phase-1 rollback (and pre-group-commit WALs)
                pending.pop(rec["tx"], None)
                if prepared is not None:
                    prepared.pop(rec["tx"], None)
            elif op == "truncate":
                # replayed in log order: discard everything replayed into
                # the table so far (≙ TRUNCATE barrier in the redo stream).
                # Secondary-index storage tables truncate with their base:
                # their redo replays alongside the base rows, so the
                # barrier must clear them identically or recovered index
                # entries would resurrect pre-truncate values.
                table = rec["table"]
                targets = [table]
                base = engine.tables.get(table)
                if base is not None:
                    targets += [ix.storage_table
                                for ix in base.tdef.indexes]
                for t in targets:
                    if e.lsn <= engine.truncate_barriers.get(t, 0):
                        # the slog already applied this truncate AND
                        # restored post-truncate direct-load segments;
                        # only clear what WAL replay put into memtables
                        engine.reset_memtables(t)
                    elif t in engine.tables:
                        engine.truncate_table(t, log=False)
                # drop buffered redo of the table (writers finish before
                # the barrier thanks to the X table lock; belt-and-braces)
                tset = set(targets)
                for recs in pending.values():
                    recs[:] = [r for r in recs if r["table"] not in tset]
        return max_ts


def _replay_ddl(op: dict, engine):
    """Apply one replicated DDL op, skipping anything the engine's own
    slog already applied (create/drop/alter become no-ops when the
    target state is already present — WAL DDL replay must never wipe
    slog-restored segments, e.g. a CTAS bulk load with no redo)."""
    kind = op.get("op")
    if kind in ("create_table", "drop_table"):
        exists = op.get("name") in engine.tables
        if (kind == "create_table" and exists) or \
                (kind == "drop_table" and not exists):
            return
    elif kind in ("alter_add", "alter_drop"):
        ts = engine.tables.get(op.get("table"))
        if ts is not None:
            cname = (op["column"][0] if kind == "alter_add"
                     else op.get("column"))
            has = any(c.name == cname for c in ts.tdef.columns)
            if (kind == "alter_add" and has) or \
                    (kind == "alter_drop" and not has):
                return
    # create_index/drop_index/truncate: engine._replay is idempotent
    engine._replay(op)


def _jsonable(values: dict) -> dict:
    out = {}
    for k, v in values.items():
        if hasattr(v, "item"):
            v = v.item()
        out[k] = v
    return out
