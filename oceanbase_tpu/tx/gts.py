"""Global timestamp service (GTS).

Reference analog: the per-tenant centralized timestamp service with local
caching (src/storage/tx/ob_gts_source.h, ob_timestamp_service.h).  The
reference persists GTS epochs through Paxos; here the monotonic source can
be seeded from the replicated log's recovery point so timestamps never go
backwards across restarts.
"""

from __future__ import annotations

import threading


class GTS:
    def __init__(self, start: int = 1):
        self._ts = start
        self._lock = threading.Lock()

    def get_ts(self) -> int:
        """Strictly monotonic timestamp (≙ gts acquisition for snapshots
        and commit versions)."""
        with self._lock:
            self._ts += 1
            return self._ts

    def current(self) -> int:
        with self._lock:
            return self._ts

    def advance_to(self, ts: int):
        """Never-go-back seeding on recovery."""
        with self._lock:
            self._ts = max(self._ts, ts)
