"""obcheck shared infrastructure: findings, pragmas, baseline diffing.

A ``Finding``'s identity (``key``) deliberately omits the line number:
baselined findings must survive unrelated edits above them, so identity
is (rule, file, function, message) and the diff is a multiset subtract —
adding a SECOND ``int()`` sync to a function that already had one is a
new finding even though the key repeats.
"""

from __future__ import annotations

import ast
import json
import os
import re
import time
from collections import Counter
from dataclasses import dataclass
from typing import Callable, Iterable, Sequence

# rule families (each checker documents its rules under one family)
FAMILIES = ("trace", "mask", "lock", "metric", "time", "io", "cancel",
            "rpc")

_PRAGMA_RE = re.compile(r"#\s*obcheck:\s*ok\(([^)]*)\)")


@dataclass(frozen=True)
class Finding:
    """One static-analysis violation."""

    rule: str      # dotted rule id, e.g. "trace.host-sync"
    path: str      # repo-relative file path
    line: int      # 1-based line of the offending node
    func: str      # enclosing function qualname ("" for module level)
    message: str   # human-readable description

    @property
    def key(self) -> str:
        """Baseline identity — line-free so edits above don't churn."""
        return f"{self.rule}|{self.path}|{self.func}|{self.message}"

    def render(self) -> str:
        where = f"{self.path}:{self.line}"
        fn = f" [{self.func}]" if self.func else ""
        return f"{where}: {self.rule}{fn}: {self.message}"


class Analyzer:
    """Parsed view of a set of source files.

    ``files`` maps repo-relative paths to source text; tests feed
    synthetic trees, the CLI feeds the real package.  Files that fail to
    parse produce a ``<family>.parse-error`` finding instead of crashing
    the run (a syntax error must fail CI loudly, not silently skip the
    file's checks).
    """

    def __init__(self, files: dict[str, str]):
        self.files = dict(files)
        self.trees: dict[str, ast.Module] = {}
        self.lines: dict[str, list[str]] = {}
        self.parse_errors: list[Finding] = []
        for path, src in self.files.items():
            self.lines[path] = src.splitlines()
            try:
                self.trees[path] = ast.parse(src)
            except SyntaxError as e:
                self.parse_errors.append(Finding(
                    rule="trace.parse-error", path=path,
                    line=e.lineno or 0, func="",
                    message=f"unparseable source: {e.msg}"))

    # -- pragmas ---------------------------------------------------------
    def pragma_rules(self, path: str, line: int) -> set[str]:
        """Pragma entries covering 1-based ``line`` (same line or the
        line directly above)."""
        out: set[str] = set()
        lines = self.lines.get(path, [])
        for ln in (line, line - 1):
            if 1 <= ln <= len(lines):
                m = _PRAGMA_RE.search(lines[ln - 1])
                if m:
                    out |= {p.strip() for p in m.group(1).split(",")
                            if p.strip()}
        return out

    def suppressed(self, path: str, line: int, rule: str) -> bool:
        """A pragma suppresses a rule by exact id or by family prefix
        (``ok(trace)`` covers every ``trace.*`` rule)."""
        for p in self.pragma_rules(path, line):
            if p == rule or rule.startswith(p + "."):
                return True
        return False

    def filter(self, findings: Iterable[Finding]) -> list[Finding]:
        """Drop pragma-suppressed findings."""
        return [f for f in findings
                if not self.suppressed(f.path, f.line, f.rule)]


# ---------------------------------------------------------------------------
# AST helpers shared by the checkers
# ---------------------------------------------------------------------------


def iter_functions(tree: ast.Module):
    """Yield (qualname, func_node, class_name|None) for every def in the
    module, including methods and nested functions.  Qualnames follow
    ``Class.method`` / ``outer.<locals>.inner`` convention."""

    def walk(node, prefix, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child, cls
                yield from walk(child, q + ".<locals>.", cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, child.name + ".", child.name)
            else:
                yield from walk(child, prefix, cls)

    yield from walk(tree, "", None)


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` attribute/name chain as a string, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def names_in(node: ast.AST) -> set[str]:
    """All bare Name identifiers referenced anywhere under ``node``."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def attrs_in(node: ast.AST) -> set[str]:
    """All attribute names accessed anywhere under ``node``."""
    return {n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)}


# ---------------------------------------------------------------------------
# file discovery
# ---------------------------------------------------------------------------


def load_package_files(root: str) -> dict[str, str]:
    """Repo-relative path -> source for every .py under the package (and
    scripts/, which hosts jit-adjacent driver code)."""
    files: dict[str, str] = {}
    for sub in ("oceanbase_tpu", "scripts"):
        base = os.path.join(root, sub)
        if not os.path.isdir(base):
            continue
        for dirpath, _dirs, names in os.walk(base):
            for n in sorted(names):
                if not n.endswith(".py"):
                    continue
                full = os.path.join(dirpath, n)
                rel = os.path.relpath(full, root).replace(os.sep, "/")
                with open(full, encoding="utf-8") as fh:
                    files[rel] = fh.read()
    return files


# ---------------------------------------------------------------------------
# run + baseline
# ---------------------------------------------------------------------------

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def run_all(files: dict[str, str],
            checkers: Sequence[Callable[[Analyzer], list[Finding]]]
            | None = None,
            timings: dict[str, float] | None = None) -> list[Finding]:
    """Run every checker over ``files``; pragma-suppressed findings are
    already dropped.  Deterministic order (path, line, rule).  When
    ``timings`` is a dict, per-checker wall time accumulates into it
    keyed by the checker's ``__name__``."""
    if checkers is None:
        from oceanbase_tpu.analysis.cancel_rules import check_cancel_rules
        from oceanbase_tpu.analysis.io_rules import check_io_rules
        from oceanbase_tpu.analysis.lock_order import check_lock_order
        from oceanbase_tpu.analysis.mask_discipline import (
            check_mask_discipline,
        )
        from oceanbase_tpu.analysis.metric_rules import check_metric_rules
        from oceanbase_tpu.analysis.rpc_rules import check_rpc_rules
        from oceanbase_tpu.analysis.time_rules import check_time_rules
        from oceanbase_tpu.analysis.trace_safety import check_trace_safety

        checkers = (check_trace_safety, check_mask_discipline,
                    check_lock_order, check_metric_rules,
                    check_time_rules, check_io_rules, check_cancel_rules,
                    check_rpc_rules)
    az = Analyzer(files)
    findings: list[Finding] = list(az.parse_errors)
    for chk in checkers:
        t0 = time.monotonic()
        findings.extend(chk(az))
        if timings is not None:
            timings[chk.__name__] = (timings.get(chk.__name__, 0.0)
                                     + time.monotonic() - t0)
    findings = az.filter(findings)
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule,
                                           f.message))


def load_baseline(path: str = BASELINE_PATH) -> Counter:
    """Baseline as a multiset of finding keys (empty when absent)."""
    if not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    return Counter({k: int(v) for k, v in data.get("counts", {}).items()})


def write_baseline(findings: Sequence[Finding],
                   path: str = BASELINE_PATH) -> dict:
    counts = Counter(f.key for f in findings)
    data = {
        "version": 1,
        "total": sum(counts.values()),
        # sorted for stable diffs of the checked-in file
        "counts": {k: counts[k] for k in sorted(counts)},
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return data


def diff_findings(findings: Sequence[Finding],
                  baseline: Counter) -> list[Finding]:
    """Findings NOT covered by the baseline multiset: the i-th repeat of
    a key is new once i exceeds the baselined count."""
    seen: Counter = Counter()
    new: list[Finding] = []
    for f in findings:
        seen[f.key] += 1
        if seen[f.key] > baseline.get(f.key, 0):
            new.append(f)
    return new
