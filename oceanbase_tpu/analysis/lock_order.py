"""lock-order checker: acquisition-graph inversions and unlocked shared
mutation (rules ``lock.*``).

Scope: the concurrent control plane — ``catalog.py``,
``storage/engine.py`` (+ the tablet/memtable/indexes structures it locks
through), ``net/node.py``, ``tx/*.py``, ``server/tenant.py``.  The
checker:

1. finds lock objects (``self.X = threading.Lock()/RLock()/Condition()``)
   — a lock's identity is ``Class.attr``;
2. walks every method tracking the held-lock stack through ``with``
   blocks (and linear ``.acquire()``/``.release()`` pairs), resolving
   calls through ``self.``, typed attributes (``self.attr = Class()``
   anywhere in scope) and unique method names, to build the
   lock-acquisition graph with per-edge witness sites; a method named
   ``*_locked`` is analyzed with its class locks held (the codebase's
   caller-holds-the-lock convention);
3. reports every cycle as ``lock.inversion`` (two threads taking the
   edges in opposite order deadlock);
4. reports container mutation of shared ``self.*`` state outside any
   held lock in lock-owning classes as ``lock.unlocked-mut``.
"""

from __future__ import annotations

import ast
import fnmatch
from dataclasses import dataclass

from oceanbase_tpu.analysis.core import Analyzer, Finding, dotted_name

SCOPE = (
    "oceanbase_tpu/catalog.py",
    "oceanbase_tpu/storage/engine.py",
    "oceanbase_tpu/storage/tablet.py",
    "oceanbase_tpu/storage/partition.py",
    "oceanbase_tpu/storage/memtable.py",
    "oceanbase_tpu/storage/indexes.py",
    "oceanbase_tpu/net/node.py",
    "oceanbase_tpu/tx/*.py",
    "oceanbase_tpu/server/tenant.py",
)

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}
_MUTATORS = {"pop", "append", "update", "add", "remove", "clear",
             "discard", "setdefault", "popitem", "insert", "extend"}
_CONTAINER_CTORS = {"dict", "list", "set", "defaultdict", "OrderedDict",
                    "Counter", "deque"}


@dataclass
class _AssignView:
    """Uniform (targets, value) view over Assign/AnnAssign nodes."""

    targets: list
    value: ast.AST


@dataclass
class _Method:
    path: str
    cls: str
    name: str
    node: ast.FunctionDef

    @property
    def qual(self) -> str:
        return f"{self.cls}.{self.name}"


class _Scope:
    def __init__(self, az: Analyzer):
        self.az = az
        self.paths = sorted(
            p for p in az.trees
            if any(fnmatch.fnmatch(p, pat) for pat in SCOPE))
        self.methods: dict[tuple[str, str], _Method] = {}  # (cls,name)
        self.by_method_name: dict[str, list[tuple[str, str]]] = {}
        self.functions: dict[str, tuple[str, ast.FunctionDef]] = {}
        self.locks: dict[str, set[str]] = {}       # cls -> lock attrs
        self.attr_type: dict[str, str] = {}        # attr name -> cls
        self.containers: dict[str, set[str]] = {}  # cls -> dict/list attrs
        cls_names: set[str] = set()
        for path in self.paths:
            for n in self.az.trees[path].body:
                if isinstance(n, ast.ClassDef):
                    cls_names.add(n.name)
        for path in self.paths:
            for n in self.az.trees[path].body:
                if isinstance(n, ast.ClassDef):
                    self._scan_class(path, n, cls_names)
                elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.functions[n.name] = (path, n)
                    self._scan_attr_types(n, cls_names)

    def _scan_class(self, path: str, cnode: ast.ClassDef,
                    cls_names: set[str]):
        self.locks.setdefault(cnode.name, set())
        self.containers.setdefault(cnode.name, set())
        for m in cnode.body:
            if not isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            meth = _Method(path, cnode.name, m.name, m)
            self.methods[(cnode.name, m.name)] = meth
            self.by_method_name.setdefault(m.name, []).append(
                (cnode.name, m.name))
            for n in ast.walk(m):
                if isinstance(n, ast.Assign):
                    tgts, val = n.targets, n.value
                elif isinstance(n, ast.AnnAssign) and n.value is not None:
                    tgts, val = [n.target], n.value
                else:
                    continue
                n = _AssignView(tgts, val)
                self_attrs = [
                    t.attr for t in n.targets
                    if isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"]
                if not self_attrs:
                    continue
                if isinstance(n.value, (ast.Dict, ast.List, ast.Set,
                                        ast.DictComp, ast.ListComp)):
                    self.containers[cnode.name].update(self_attrs)
                elif isinstance(n.value, ast.Call):
                    d = dotted_name(n.value.func) or ""
                    last = d.split(".")[-1]
                    if last in _LOCK_CTORS:
                        self.locks[cnode.name].update(self_attrs)
                    elif last in cls_names:
                        for a in self_attrs:
                            self.attr_type[a] = last
                    elif last in _CONTAINER_CTORS:
                        self.containers[cnode.name].update(self_attrs)
            self._scan_attr_types(m, cls_names)

    def _scan_attr_types(self, fnode, cls_names: set[str]):
        """``<anything>.attr = ClassName(...)`` anywhere in scope types
        the attribute (covers late wiring like svc.lock_table = ...)."""
        for n in ast.walk(fnode):
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                last = (dotted_name(n.value.func) or "").split(".")[-1]
                if last not in cls_names:
                    continue
                for t in n.targets:
                    if isinstance(t, ast.Attribute):
                        self.attr_type.setdefault(t.attr, last)

    # -- lock identity ---------------------------------------------------
    def lock_of(self, cls: str, expr: ast.AST) -> str | None:
        """``self.X`` (or ``<name>.X``) naming a known lock attr."""
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and expr.attr in self.locks.get(
                        cls, ()):
                    return f"{cls}.{expr.attr}"
                # cond.wait()/x._lock style receivers: match any class
                # holding a lock attr of this name via typed attributes
                owner = self.attr_type.get(base.id)
                if owner and expr.attr in self.locks.get(owner, ()):
                    return f"{owner}.{expr.attr}"
        return None

    # -- call resolution -------------------------------------------------
    def resolve(self, cls: str, call: ast.Call) -> list[tuple[str, str]]:
        f = call.func
        if isinstance(f, ast.Name):
            if f.id in self.functions:
                return [("", f.id)]  # module-level function
            return []
        if not isinstance(f, ast.Attribute):
            return []
        base = f.value
        if isinstance(base, ast.Name) and base.id == "self":
            if (cls, f.attr) in self.methods:
                return [(cls, f.attr)]
            return []
        # typed attribute receiver: self.attr.m() / svc.attr.m()
        if isinstance(base, ast.Attribute):
            owner = self.attr_type.get(base.attr)
            if owner and (owner, f.attr) in self.methods:
                return [(owner, f.attr)]
            return []
        # bare-name receiver: resolve only when the method name is
        # specific (defined by at most 2 scoped classes) — generic names
        # like get/write on arbitrary receivers would fabricate edges
        if isinstance(base, ast.Name):
            owner = self.attr_type.get(base.id)
            if owner and (owner, f.attr) in self.methods:
                return [(owner, f.attr)]
            cands = self.by_method_name.get(f.attr, [])
            if 0 < len(cands) <= 2:
                return list(cands)
        return []


# ---------------------------------------------------------------------------
# per-method walk: held-lock stack + events
# ---------------------------------------------------------------------------


@dataclass
class _Summary:
    acquires: set[str]
    calls: list[tuple[tuple[str, str], int]]  # (callee, line)
    # (held, acquired, line) for direct nested acquisition
    nested: list[tuple[str, str, int]]
    # calls made while holding: (held locks, callee, line)
    held_calls: list[tuple[frozenset, tuple[str, str], int]]
    # shared-container mutations outside any lock: (attr, line, how)
    unlocked_muts: list[tuple[str, int, str]]


def _mutated_self_attr(node: ast.AST) -> tuple[str, str] | None:
    """Container mutation of ``self.<attr>`` -> (attr, kind)."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr in _MUTATORS:
        recv = node.func.value
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and recv.value.id == "self":
            return recv.attr, f".{node.func.attr}()"
    tgts: list[ast.AST] = []
    if isinstance(node, ast.Assign):
        tgts = list(node.targets)
    elif isinstance(node, ast.AugAssign):
        tgts = [node.target]
    elif isinstance(node, ast.Delete):
        tgts = list(node.targets)
    for t in tgts:
        while isinstance(t, ast.Subscript):
            t = t.value
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and t.value.id == "self":
                return t.attr, "[...] store"
    return None


def _stmt_exprs(st: ast.stmt):
    """The statement's own expression children (bodies excluded — those
    are visited as statements with their own held set)."""
    for _name, val in ast.iter_fields(st):
        if isinstance(val, ast.expr):
            yield val
        elif isinstance(val, list):
            for v in val:
                if isinstance(v, ast.expr):
                    yield v


def _walk(scope: _Scope, meth: _Method, summ: _Summary):
    lock_attrs = scope.locks.get(meth.cls, set())
    container_attrs = scope.containers.get(meth.cls, set())

    def record_mut(attr_how, line, held):
        attr, how = attr_how
        # only KNOWN shared containers: self.obj.append() on a component
        # object is a method call, that object's own lock's concern
        if not held and lock_attrs and attr in container_attrs:
            summ.unlocked_muts.append((attr, line, how))

    def scan_expr(expr: ast.AST, held: tuple[str, ...]):
        for n in ast.walk(expr):
            if not isinstance(n, ast.Call):
                continue
            if isinstance(n.func, ast.Attribute) and n.func.attr in (
                    "acquire", "release", "wait", "notify",
                    "notify_all") and scope.lock_of(
                        meth.cls, n.func.value) is not None:
                if n.func.attr == "acquire":
                    lk = scope.lock_of(meth.cls, n.func.value)
                    for h in held:
                        if h != lk:
                            summ.nested.append((h, lk, n.lineno))
                    summ.acquires.add(lk)
                continue
            mut = _mutated_self_attr(n)
            if mut is not None:
                record_mut(mut, n.lineno, held)
                continue
            for tgt in scope.resolve(meth.cls, n):
                summ.calls.append((tgt, n.lineno))
                if held:
                    summ.held_calls.append(
                        (frozenset(held), tgt, n.lineno))

    def visit(stmts, held: tuple[str, ...]):
        held_list = list(held)
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested defs are separate analysis units
            if isinstance(st, ast.With):
                new = list(held_list)
                for item in st.items:
                    lk = scope.lock_of(meth.cls, item.context_expr)
                    if lk is not None:
                        for h in new:
                            if h != lk:
                                summ.nested.append((h, lk, st.lineno))
                        summ.acquires.add(lk)
                        new.append(lk)
                    else:
                        scan_expr(item.context_expr, tuple(held_list))
                visit(st.body, tuple(new))
                continue
            # linear acquire()/release() statements on our own locks
            if isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
                c = st.value
                if isinstance(c.func, ast.Attribute) and \
                        c.func.attr in ("acquire", "release"):
                    lk = scope.lock_of(meth.cls, c.func.value)
                    if lk is not None:
                        if c.func.attr == "acquire":
                            for h in held_list:
                                if h != lk:
                                    summ.nested.append((h, lk, st.lineno))
                            summ.acquires.add(lk)
                            held_list.append(lk)
                        elif lk in held_list:
                            held_list.remove(lk)
                        continue
            mut = _mutated_self_attr(st)
            if mut is not None:
                record_mut(mut, st.lineno, tuple(held_list))
            for expr in _stmt_exprs(st):
                scan_expr(expr, tuple(held_list))
            for fld in ("body", "orelse", "finalbody"):
                sub = getattr(st, fld, None)
                if sub:
                    visit(sub, tuple(held_list))
            for h in getattr(st, "handlers", []) or []:
                visit(h.body, tuple(held_list))

    # the ``_locked`` suffix is the codebase's caller-holds-the-lock
    # convention: analyze the body as if every class lock were held
    # (mutations are covered; calls out still contribute edges FROM the
    # held locks, which is exactly what the caller's context implies)
    initial: tuple[str, ...] = ()
    if meth.name.endswith("_locked"):
        initial = tuple(f"{meth.cls}.{a}" for a in sorted(lock_attrs))
        summ.acquires.update(initial)
    visit(meth.node.body, initial)


def check_lock_order(az: Analyzer) -> list[Finding]:
    scope = _Scope(az)
    summaries: dict[tuple[str, str], _Summary] = {}
    for key, meth in scope.methods.items():
        s = _Summary(set(), [], [], [], [])
        _walk(scope, meth, s)
        summaries[key] = s
    # module-level functions participate in resolution targets
    for name, (path, fnode) in scope.functions.items():
        meth = _Method(path, "", name, fnode)
        s = _Summary(set(), [], [], [], [])
        _walk(scope, meth, s)
        summaries[("", name)] = s

    # transitive acquisition sets (fixpoint)
    changed = True
    trans: dict[tuple[str, str], set[str]] = {
        k: set(s.acquires) for k, s in summaries.items()}
    while changed:
        changed = False
        for k, s in summaries.items():
            for callee, _ln in s.calls:
                extra = trans.get(callee, set()) - trans[k]
                if extra:
                    trans[k] |= extra
                    changed = True

    # lock graph edges with witnesses
    edges: dict[tuple[str, str], tuple[str, int, str]] = {}

    def add_edge(a: str, b: str, path: str, line: int, qual: str):
        if a != b and (a, b) not in edges:
            edges[(a, b)] = (path, line, qual)

    for key, s in summaries.items():
        meth = scope.methods.get(key)
        path = meth.path if meth else scope.functions[key[1]][0]
        qual = meth.qual if meth else key[1]
        for h, lk, ln in s.nested:
            add_edge(h, lk, path, ln, qual)
        for held, callee, ln in s.held_calls:
            for h in held:
                for lk in trans.get(callee, ()):
                    add_edge(h, lk, path, ln, qual)

    findings: list[Finding] = []

    # cycle detection over the lock graph
    adj: dict[str, set[str]] = {}
    for a, b in edges:
        adj.setdefault(a, set()).add(b)

    def find_cycles() -> list[tuple[str, ...]]:
        cycles: set[tuple[str, ...]] = set()
        for start in sorted(adj):
            stack = [(start, (start,))]
            while stack:
                node, trail = stack.pop()
                for nxt in sorted(adj.get(node, ())):
                    if nxt == start:
                        # canonical rotation for stable identity
                        cyc = trail
                        i = cyc.index(min(cyc))
                        cycles.add(cyc[i:] + cyc[:i])
                    elif nxt not in trail and len(trail) < 6:
                        stack.append((nxt, trail + (nxt,)))
        return sorted(cycles)

    for cyc in find_cycles():
        a, b = cyc[0], cyc[1 % len(cyc)]
        wit = edges.get((a, b)) or next(iter(edges.values()))
        path, line, qual = wit
        order = " -> ".join(cyc + (cyc[0],))
        findings.append(Finding(
            "lock.inversion", path, line, qual,
            f"lock-order cycle {order}: two threads taking these in "
            f"opposite order deadlock"))

    for key, s in summaries.items():
        meth = scope.methods.get(key)
        if meth is None:
            continue
        seen: set[tuple[str, str]] = set()
        for attr, line, how in s.unlocked_muts:
            if meth.name.startswith("__init__"):
                continue
            if (attr, how) in seen:  # one finding per attr/kind per method
                continue
            seen.add((attr, how))
            findings.append(Finding(
                "lock.unlocked-mut", meth.path, line, meth.qual,
                f"self.{attr}{how} mutates shared state outside "
                f"{meth.cls}'s lock"))
    return findings
