"""rpc verb/policy coherence checker (rules ``rpc.*``).

The robustness plane's standing contract (ROADMAP, PR 4): every wire
verb a node serves has an explicit ``net/rpc.py::POLICIES`` entry
(deadline + idempotency declared up front, not discovered in an
outage), no non-idempotent verb rides a resend loop, and bulk-payload
replies carry a digest field the client can verify.

The checker parses the ``POLICIES`` dict literal straight out of
``net/rpc.py`` (no import — works on synthetic trees too), collects
served verbs from handler-dict literals and ``.register(...)`` calls in
the handler surface, and cross-references:

- ``rpc.missing-policy``       — a served verb with no ``POLICIES``
                                 entry rides ``DEFAULT_POLICY`` blind
                                 (flagged at the registration site);
- ``rpc.nonidempotent-resend`` — a ``.call(...)`` of a non-idempotent
                                 (or unknown) verb inside a retry loop
                                 that swallows transport errors — the
                                 classic double-apply window;
- ``rpc.bulk-no-digest``       — a handler reply dict shipping a bulk
                                 payload key with no sibling crc/digest
                                 field (the wire twin of
                                 ``io.unverified-write``).
"""

from __future__ import annotations

import ast
import fnmatch
import re

from oceanbase_tpu.analysis.core import (
    Analyzer,
    Finding,
    dotted_name,
)
from oceanbase_tpu.analysis.trace_safety import _Index

#: where POLICIES lives
POLICY_FILE = "oceanbase_tpu/net/rpc.py"

#: files whose dict literals / register() calls serve wire verbs
HANDLER_GLOBS = (
    "oceanbase_tpu/net/*.py",
    "oceanbase_tpu/palf/*.py",
)

#: where non-idempotent-resend discipline applies (client call sites)
RESEND_SCOPE = (
    "oceanbase_tpu/net/*.py",
    "oceanbase_tpu/palf/*.py",
    "oceanbase_tpu/px/*.py",
    "oceanbase_tpu/exec/*.py",
    "oceanbase_tpu/storage/*.py",
    "oceanbase_tpu/server/*.py",
)

#: reply keys that mean "bulk payload" (rows, chunk bytes, manifests)
BULK_KEYS = {"data", "arrays", "manifest", "slog", "payload"}

#: sibling key substrings that count as a digest field
_DIGESTISH = ("crc", "digest", "checksum")

_VERB_RE = re.compile(r"^[a-z_]+\.[a-z_]+$")


def _globbed(az: Analyzer, pats) -> list[str]:
    return [p for p in az.trees
            if any(fnmatch.fnmatch(p, pat) for pat in pats)]


def _parse_policies(az: Analyzer) -> dict[str, bool] | None:
    """verb -> idempotent? from the POLICIES dict literal, or None when
    the policy file isn't in the analyzed set (synthetic trees)."""
    tree = az.trees.get(POLICY_FILE)
    if tree is None:
        return None
    policies: dict[str, bool] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):  # POLICIES: dict[...] = {..}
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "POLICIES"
                   for t in targets):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if not (isinstance(k, ast.Constant) and
                    isinstance(k.value, str)):
                continue
            idem = False
            if isinstance(v, ast.Call):
                if len(v.args) >= 2 and \
                        isinstance(v.args[1], ast.Constant):
                    idem = bool(v.args[1].value)
                for kw in v.keywords:
                    if kw.arg == "idempotent" and \
                            isinstance(kw.value, ast.Constant):
                        idem = bool(kw.value.value)
            policies[k.value] = idem
    return policies


def _looks_like_verb(s: str) -> bool:
    return s == "ping" or bool(_VERB_RE.match(s))


def _served_verbs(az: Analyzer) -> list[tuple[str, int, str]]:
    """(verb, lineno, path) for every registration site: dict literals
    mapping verb strings to handler callables (not Constants, not
    ``VerbPolicy(...)``-style Calls — that shape is POLICIES itself),
    plus ``.register("verb", fn)`` calls."""
    out: list[tuple[str, int, str]] = []
    for path in _globbed(az, HANDLER_GLOBS):
        for node in ast.walk(az.trees[path]):
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if not (isinstance(k, ast.Constant) and
                            isinstance(k.value, str) and
                            _looks_like_verb(k.value)):
                        continue
                    if isinstance(v, (ast.Constant, ast.Call)):
                        continue
                    out.append((k.value, k.lineno, path))
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "register" and node.args and \
                    isinstance(node.args[0], ast.Constant) and \
                    isinstance(node.args[0].value, str) and \
                    _looks_like_verb(node.args[0].value):
                out.append((node.args[0].value, node.lineno, path))
    return out


def _call_verb(call: ast.Call) -> str | None:
    f = call.func
    if not (isinstance(f, ast.Attribute) and
            f.attr in ("call", "call_with_size")):
        return None
    if call.args and isinstance(call.args[0], ast.Constant) and \
            isinstance(call.args[0].value, str) and \
            _looks_like_verb(call.args[0].value):
        return call.args[0].value
    return None


def _swallowing_try(try_node: ast.Try) -> bool:
    """At least one except handler does not end by re-raising — the
    error is absorbed and the loop comes back around."""
    for h in try_node.handlers:
        if not h.body or not isinstance(h.body[-1], ast.Raise):
            return True
    return False


def _resend_sites(fnode: ast.AST) -> list[ast.Call]:
    """``.call(verb, ...)`` sites lexically inside a loop AND inside a
    try whose except swallows — the resend-ladder shape."""
    out: list[ast.Call] = []

    def visit(node, in_loop, in_swallow):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            loop = in_loop or isinstance(child, (ast.For, ast.While))
            swallow = in_swallow or (isinstance(child, ast.Try) and
                                     _swallowing_try(child))
            if loop and swallow and isinstance(child, ast.Call) and \
                    _call_verb(child) is not None:
                out.append(child)
            visit(child, loop, swallow)

    visit(fnode, False, False)
    return out


def _dict_returns(fnode: ast.AST) -> list[ast.Dict]:
    out = []
    for n in ast.walk(fnode):
        if isinstance(n, ast.Return) and isinstance(n.value, ast.Dict):
            out.append(n.value)
    return out


def _dict_keys(d: ast.Dict) -> list[str]:
    return [k.value for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)]


def check_rpc_rules(az: Analyzer) -> list[Finding]:
    policies = _parse_policies(az)
    idx = _Index(az)
    out: list[Finding] = []

    # rpc.missing-policy — every served verb declared up front
    if policies is not None:
        seen: set[tuple[str, str, int]] = set()
        for verb, lineno, path in _served_verbs(az):
            if verb in policies:
                continue
            key = (verb, path, lineno)
            if key in seen:
                continue
            seen.add(key)
            out.append(Finding(
                "rpc.missing-policy", path, lineno, None,
                f"served verb {verb!r} has no net/rpc.py POLICIES "
                f"entry — it rides DEFAULT_POLICY with an undeclared "
                f"deadline and idempotency"))

    # rpc.nonidempotent-resend — client-side double-apply windows
    for path in _globbed(az, RESEND_SCOPE):
        for (p, qual), info in idx.funcs.items():
            if p != path:
                continue
            for call in _resend_sites(info.node):
                verb = _call_verb(call)
                idem = (policies or {}).get(verb, False)
                if idem:
                    continue
                known = policies is not None and verb in policies
                out.append(Finding(
                    "rpc.nonidempotent-resend", p, call.lineno, qual,
                    f"{'non-idempotent' if known else 'unknown-policy'} "
                    f"verb {verb!r} called from an error-swallowing "
                    f"retry loop: a transport error after the request "
                    f"hit the wire re-applies the side effect"))

    # rpc.bulk-no-digest — handler replies ship verifiable payloads
    bulk_files = set(_globbed(az, HANDLER_GLOBS))
    if "oceanbase_tpu/px/dtl.py" in az.trees:
        bulk_files.add("oceanbase_tpu/px/dtl.py")
    for path in sorted(bulk_files):
        for (p, qual), info in idx.funcs.items():
            if p != path:
                continue
            for d in _dict_returns(info.node):
                keys = _dict_keys(d)
                bulk = [k for k in keys if k in BULK_KEYS]
                if not bulk:
                    continue
                if any(any(t in k.lower() for t in _DIGESTISH)
                       for k in keys):
                    continue
                out.append(Finding(
                    "rpc.bulk-no-digest", p, d.lineno, qual,
                    f"reply ships bulk payload {bulk[0]!r} with no "
                    f"crc/digest sibling field — the peer cannot "
                    f"verify what it received (see dtl.verify_reply)"))
    return out
