"""obcheck: static analysis enforcing the engine's silent invariants.

PR 2 (shape buckets) made correctness rest on two contracts nothing
enforced: a masked-dead pad lane must never influence a result, and a
jitted operator body must never break trace stability (host syncs,
identity-hashed cache keys, Python branches on tracers).  TVM and Tensor
Processing Primitives (PAPERS.md) both push kernel contracts into
compiler-side verification; this package is that layer for the TPU
build.

Three AST checkers plus one dynamic verifier:

- ``trace_safety``     — host syncs / retrace hazards in jit-reachable
                         code (rules ``trace.*``);
- ``mask_discipline``  — every operator that reads Relation/Column data
                         consumes or propagates ``mask`` (rules
                         ``mask.*``);
- ``lock_order``       — lock-acquisition graph inversions and shared-
                         dict mutation outside any held lock (rules
                         ``lock.*``);
- ``metric_rules``     — metrics-plane discipline: no updates in
                         jit-reachable code, every series name a
                         registered literal (rules ``metric.*``);
- ``poison``           — the executable half: fill pad lanes with
                         NaN/sentinel garbage and assert bit-identical
                         results.

Audited exceptions carry a ``# obcheck: ok(<rule>)`` pragma; everything
else diffs against the checked-in baseline (``analysis/baseline.json``)
so only NEW violations fail CI.  Driver: ``scripts/obcheck.py``.
"""

from oceanbase_tpu.analysis.core import (
    Analyzer,
    Finding,
    diff_findings,
    load_baseline,
    load_package_files,
    run_all,
    write_baseline,
)
from oceanbase_tpu.analysis.lock_order import check_lock_order
from oceanbase_tpu.analysis.mask_discipline import check_mask_discipline
from oceanbase_tpu.analysis.metric_rules import check_metric_rules
from oceanbase_tpu.analysis.trace_safety import check_trace_safety

__all__ = [
    "Analyzer",
    "Finding",
    "check_lock_order",
    "check_mask_discipline",
    "check_metric_rules",
    "check_trace_safety",
    "diff_findings",
    "load_baseline",
    "load_package_files",
    "run_all",
    "write_baseline",
]
