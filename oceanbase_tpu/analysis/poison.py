"""Poison-lane verifier: the Static-shape policy as an executable check.

Every operator must treat masked-dead pad lanes as if they did not
exist.  The static half of that contract is ``mask_discipline``; this is
the dynamic half: fill the dead lanes of a relation with adversarial
garbage — NaN payloads in float columns, a loud bit pattern in int
columns, out-of-range codes in string columns, and (worst case) validity
bits flipped to True — then re-run the query and require *bit-identical*
results.  A pad lane that influences anything shows up as a diff.

Poison values are deliberately hostile:

- float    -> NaN (breaks any unmasked arithmetic/compare)
- int      -> 0x5AD5AD5AD5AD5AD5-ish sentinel (breaks unmasked sums)
- bool     -> True (breaks unmasked counts)
- strings  -> code -1 (the reserved NULL payload; must stay clamped)
- valid    -> True on dead lanes (operators must gate on mask, not
              validity)

Use ``poison_pad_lanes`` on one relation, ``poison_tables`` on a plan's
input dict, or ``assert_poison_invariant`` to run the whole
clean-vs-poisoned comparison.  Tests get these via the ``poison``
fixture in ``tests/conftest.py``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from oceanbase_tpu.datatypes import TypeKind
from oceanbase_tpu.vector.column import Column, Relation

INT_POISON = np.int64(0x5AD5AD5AD5AD5AD)  # loud, sign-safe bit pattern


def _poison_data(data, dead, dtype_kind):
    if dtype_kind == TypeKind.STRING:
        return jnp.where(dead, jnp.asarray(-1, data.dtype), data)
    if jnp.issubdtype(data.dtype, jnp.floating):
        if data.ndim == 2:  # vector columns: poison whole rows
            return jnp.where(dead[:, None], jnp.nan, data)
        return jnp.where(dead, jnp.asarray(jnp.nan, data.dtype), data)
    if data.dtype == jnp.bool_:
        return jnp.where(dead, True, data)
    return jnp.where(dead, jnp.asarray(INT_POISON, data.dtype), data)


def poison_pad_lanes(rel: Relation) -> Relation:
    """Fill masked-dead lanes with adversarial garbage (payload AND
    validity).  A relation with no dead lanes returns equivalent data."""
    mask = rel.mask_or_true()
    dead = ~mask
    cols = {}
    for name, c in rel.columns.items():
        data = _poison_data(c.data, dead, c.dtype.kind)
        valid = c.valid
        if valid is not None:
            # dead lanes become "valid": only the mask may save us
            valid = jnp.where(dead, True, valid)
        cols[name] = Column(data, valid, c.dtype, c.sdict)
    return Relation(columns=cols, mask=mask)


def poison_tables(tables: dict) -> dict:
    return {name: poison_pad_lanes(rel) for name, rel in tables.items()}


def results_identical(a: dict, b: dict) -> tuple[bool, str]:
    """Bit-identical comparison of two ``to_numpy`` result dicts.
    Returns (ok, first difference description)."""
    if sorted(a) != sorted(b):
        return False, f"column sets differ: {sorted(a)} vs {sorted(b)}"
    for k in sorted(a):
        x, y = np.asarray(a[k]), np.asarray(b[k])
        if x.shape != y.shape:
            return False, f"{k}: shape {x.shape} vs {y.shape}"
        if x.dtype == object or y.dtype == object:
            if list(map(repr, x.reshape(-1))) != \
                    list(map(repr, y.reshape(-1))):
                return False, f"{k}: object values differ"
            continue
        # bit-level equality: NaN == NaN, -0.0 != 0.0
        if x.tobytes() != y.tobytes():
            return False, f"{k}: payload bits differ"
    return True, ""


def assert_poison_invariant(run, tables: dict, materialize=None) -> None:
    """Run ``run(tables)`` clean and poisoned; assert bit-identical
    results.  ``run`` maps {name: Relation} -> Relation (e.g. a bound
    ``execute_plan``); ``materialize`` overrides the host conversion
    (defaults to ``vector.to_numpy``)."""
    from oceanbase_tpu.vector import to_numpy

    mat = materialize or to_numpy
    clean = mat(run(tables))
    poisoned = mat(run(poison_tables(tables)))
    ok, why = results_identical(clean, poisoned)
    assert ok, (
        f"poison-lane invariant violated: {why} — a masked-dead pad "
        f"lane influenced the result (Static-shape policy, ROADMAP)")
