"""metric checker: metrics-plane discipline (rules ``metric.*``).

The metrics registry (server/metrics.py) buys its ≤2% overhead budget
with two contracts this checker enforces statically:

- **host-side only** (``metric.jit-reachable``) — a metric update inside
  jit-traced code would either run once at trace time (silently wrong
  counts) or force a host sync per execution; updates belong at the same
  result/span-close boundaries the trace spans instrument.  The scope is
  the SAME computed closure trace_safety uses: functions reachable from
  ``jax.jit``/``shard_map`` roots.
- **declared names only** (``metric.undeclared`` /
  ``metric.dynamic-name``) — every series name passed to
  ``inc``/``observe``/``set_gauge`` must be a string literal registered
  by a ``declare(...)`` call somewhere in the package (or a module-level
  constant bound to a ``declare(...)`` result).  A dynamically formatted
  name (f-string, ``%``/``+``/``.format`` build, loop variable) can typo
  itself into a fresh series that nothing ever reads — the cardinality
  leak Prometheus operators know too well.

Both rules fire only on calls that resolve to the metrics module
(``from oceanbase_tpu.server import metrics [as qmetrics]`` attribute
calls, or names from-imported out of ``oceanbase_tpu.server.metrics``);
an unrelated object's ``.observe(...)`` is not our business.
"""

from __future__ import annotations

import ast

from oceanbase_tpu.analysis.core import Analyzer, Finding
from oceanbase_tpu.analysis.trace_safety import (
    _device_scope,
    _Index,
    _traced_roots,
)

METRICS_MODULE = "oceanbase_tpu.server.metrics"
UPDATE_FNS = ("inc", "observe", "set_gauge")


def _metrics_aliases(idx: _Index, path: str) -> set[str]:
    """Local names that refer to the metrics MODULE in ``path``
    (``import ... as qmetrics`` / ``from oceanbase_tpu.server import
    metrics``)."""
    out: set[str] = set()
    for alias, mod in idx.alias.get(path, {}).items():
        if mod == METRICS_MODULE:
            out.add(alias)
    for alias, (mod, orig) in idx.from_imp.get(path, {}).items():
        if f"{mod}.{orig}" == METRICS_MODULE:
            out.add(alias)
    return out


def _direct_imports(idx: _Index, path: str) -> dict[str, str]:
    """{local name: metrics function} for ``from ...metrics import inc``."""
    out: dict[str, str] = {}
    for alias, (mod, orig) in idx.from_imp.get(path, {}).items():
        if mod == METRICS_MODULE and orig in UPDATE_FNS + ("declare",):
            out[alias] = orig
    return out


def _classify_call(idx: _Index, path: str, call: ast.Call) -> str | None:
    """-> 'inc' | 'observe' | 'set_gauge' | 'declare' when ``call`` is a
    metrics-module call, else None."""
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id in _metrics_aliases(idx, path) and \
                f.attr in UPDATE_FNS + ("declare",):
            return f.attr
        return None
    if isinstance(f, ast.Name):
        return _direct_imports(idx, path).get(f.id)
    return None


def _declared_names(idx: _Index) -> tuple[set[str], set[tuple[str, str]]]:
    """Collect the registry: literal first arguments of every
    ``declare(...)`` call, plus (path, name) pairs for module-level
    constants bound to a declare() result (``M_FOO = declare("foo",
    ...)`` — declare returns the name)."""
    names: set[str] = set()
    consts: set[tuple[str, str]] = set()
    for path, tree in idx.az.trees.items():
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) and \
                    _classify_call(idx, path, n) == "declare":
                if n.args and isinstance(n.args[0], ast.Constant) \
                        and isinstance(n.args[0].value, str):
                    names.add(n.args[0].value)
            if isinstance(n, ast.Assign) and \
                    isinstance(n.value, ast.Call) and \
                    _classify_call(idx, path, n.value) == "declare":
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        consts.add((path, t.id))
    return names, consts


def check_metric_rules(az: Analyzer) -> list[Finding]:
    idx = _Index(az)
    scope = _device_scope(idx, _traced_roots(idx))
    declared, consts = _declared_names(idx)
    # metrics.py itself implements the registry — its internal calls are
    # the machinery, not call sites
    metrics_path = None
    for p in az.trees:
        if p.endswith("server/metrics.py"):
            metrics_path = p
    out: list[Finding] = []
    for (path, qual), info in idx.funcs.items():
        if path == metrics_path:
            continue
        for call in info.calls:
            kind = _classify_call(idx, path, call)
            if kind is None or kind == "declare":
                continue
            if (path, qual) in scope:
                out.append(Finding(
                    "metric.jit-reachable", path, call.lineno, qual,
                    f"metrics.{kind}(...) in jit-reachable code: the "
                    f"update runs at trace time (wrong counts) or syncs "
                    f"the host per execution — move it to the result "
                    f"boundary"))
            if not call.args:
                continue
            a = call.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                if a.value not in declared:
                    out.append(Finding(
                        "metric.undeclared", path, call.lineno, qual,
                        f"metric name {a.value!r} was never "
                        f"declare()d: updates to it raise at runtime"))
            elif isinstance(a, ast.Name) and (path, a.id) in consts:
                pass  # module-level NAME = declare("...") constant
            else:
                out.append(Finding(
                    "metric.dynamic-name", path, call.lineno, qual,
                    f"dynamically built metric name "
                    f"({ast.unparse(a)[:60]}): a typo mints a fresh "
                    f"series silently — use a declared literal (put "
                    f"variability in labels)"))
    return out
