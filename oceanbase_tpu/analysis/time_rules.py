"""time checker: monotonic-clock discipline (rules ``time.*``).

The PR 4 timing contract — ``time.time()`` is a RECORD timestamp,
elapsed measurements come from ``time.monotonic()`` /
``time.perf_counter()`` — has been enforced by review comments since.
This checker makes it static:

- **``time.wall-elapsed``** — subtracting two wall-clock samples taken
  in the same code (``time.time() - t0`` where ``t0 = time.time()``, or
  ``t1 - t0`` with both wall locals) measures elapsed time with a clock
  that steps under NTP adjustment: a latency histogram can record
  negative or hour-long "durations" during a step.  Only LOCAL wall
  samples pair into a finding — ``time.time() - record.ts`` is an
  age-of-record computation against a stored timestamp and stays legal
  (stored wall timestamps are the only thing that survives a restart).

Audited exceptions carry ``# obcheck: ok(time.wall-elapsed)``; the
baseline ships empty — the tree is clean and must stay so.
"""

from __future__ import annotations

import ast

from oceanbase_tpu.analysis.core import Analyzer, Finding


def _time_module_aliases(tree: ast.Module) -> tuple[set[str], set[str]]:
    """-> (names bound to the ``time`` MODULE, names bound to the
    ``time.time`` FUNCTION) at any import site in the file."""
    mods: set[str] = set()
    fns: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Import):
            for a in n.names:
                if a.name == "time":
                    mods.add(a.asname or "time")
        elif isinstance(n, ast.ImportFrom):
            if n.module == "time":
                for a in n.names:
                    if a.name == "time":
                        fns.add(a.asname or "time")
    return mods, fns


def _is_wall_call(node, mods: set[str], fns: set[str]) -> bool:
    """Is ``node`` a direct ``time.time()`` / imported ``time()`` call?"""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        return f.value.id in mods and f.attr == "time"
    if isinstance(f, ast.Name):
        return f.id in fns
    return False


def check_time_rules(az: Analyzer) -> list[Finding]:
    out: list[Finding] = []
    for path, tree in az.trees.items():
        mods, fns = _time_module_aliases(tree)
        if not mods and not fns:
            continue

        def scan_scope(body_nodes, qual, inherited: frozenset):
            """One function (or module/class) scope: collect locals
            assigned from wall-clock calls, then flag subtractions
            pairing two wall samples.  ``inherited`` carries enclosing
            scopes' wall names (closures)."""
            wall = set(inherited)
            subs = []
            nested = []  # (child scope body, child qual)

            def visit(node):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    sep = ".<locals>." if qual else ""
                    nested.append((node.body, f"{qual}{sep}{node.name}"))
                    return  # its body is its own scope
                if isinstance(node, ast.ClassDef):
                    # a class body is not a closure scope: its methods
                    # get Class.method qualnames with a fresh wall set
                    for c in node.body:
                        if isinstance(c, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                            nested.append(
                                (c.body, f"{node.name}.{c.name}"))
                        else:
                            visit(c)
                    return
                if isinstance(node, ast.Assign) and \
                        _is_wall_call(node.value, mods, fns):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            wall.add(t.id)
                if isinstance(node, ast.AnnAssign) and \
                        node.value is not None and \
                        _is_wall_call(node.value, mods, fns) and \
                        isinstance(node.target, ast.Name):
                    wall.add(node.target.id)
                if isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.Sub):
                    subs.append(node)
                for c in ast.iter_child_nodes(node):
                    visit(c)

            for n in body_nodes:
                visit(n)

            def is_wall_sample(e) -> bool:
                if _is_wall_call(e, mods, fns):
                    return True
                return isinstance(e, ast.Name) and e.id in wall

            for s in subs:
                if is_wall_sample(s.left) and is_wall_sample(s.right):
                    out.append(Finding(
                        "time.wall-elapsed", path, s.lineno, qual,
                        "elapsed measured as a wall-clock delta "
                        f"({ast.unparse(s)[:60]}): time.time() steps "
                        "under NTP — use time.monotonic() / "
                        "perf_counter() for durations (wall stays for "
                        "record timestamps)"))
            frozen = frozenset(wall)
            for child_body, child_qual in nested:
                scan_scope(child_body, child_qual, frozen)

        scan_scope(tree.body, "", frozenset())
    return out
