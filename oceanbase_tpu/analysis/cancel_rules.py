"""cancel-discipline checker: long host loops observe checkpoints
(rules ``cancel.*``).

The overload-safety plane's standing contract (ROADMAP, PR 13): every
host-side loop that can block — chunk iteration over the wire, peer
fan-out polling, retry ladders, bulk file copies — must reach
``server/admission.py::checkpoint()`` (or a registered equivalent) in
its body's call closure, so a KILL / statement deadline / shutdown is
observed within one iteration instead of after the whole transfer.

Detection is trigger-based: a loop is *blocking* when its body's
transitive call closure contains an RPC round-trip (``.call``/
``.call_with_size``/``.ping``), a ``time.sleep``, a ``shutil`` bulk
copy, or a subprocess — and *observing* when the same closure reaches
the admission checkpoint, a ``StmtCtx.check()``, a stop/cancel-named
event wait, or a ``CHECKPOINT_EQUIV`` registrant.  Pure-CPU loops are
out of scope (the statement-path result-boundary checkpoints own them).

Rules:

- ``cancel.loop-no-checkpoint``     — blocking loop with no observation
                                      point in its closure;
- ``cancel.fanout-no-propagation``  — RPC fan-out (threads spawned in a
                                      loop/comprehension whose target
                                      closure does RPC) with no
                                      cancellation-propagation path
                                      (the ``dtl.cancel`` pattern) and
                                      no stop-event plumbing;
- ``cancel.unknown-exempt`` / ``cancel.stale-exempt`` — registry
  hygiene for ``CANCEL_EXEMPT`` (mirrors mask_discipline.CONTRACTS).
"""

from __future__ import annotations

import ast
import fnmatch

from oceanbase_tpu.analysis.core import (
    Analyzer,
    Finding,
    dotted_name,
)
from oceanbase_tpu.analysis.trace_safety import _Index

#: the blocking-loop surface under contract
CANCEL_SCOPE = (
    "oceanbase_tpu/exec/*.py",
    "oceanbase_tpu/px/*.py",
    "oceanbase_tpu/net/*.py",
    "oceanbase_tpu/storage/scrub.py",
    "oceanbase_tpu/server/backup.py",
)

ADMISSION_MODULE = "oceanbase_tpu.server.admission"

#: audited exceptions: qualname (per file) -> why the loop may block
#: without an admission checkpoint.  Function-level; single loop sites
#: prefer an inline ``# obcheck: ok(cancel.loop-no-checkpoint)``.
CANCEL_EXEMPT: dict[str, dict[str, str]] = {
    "oceanbase_tpu/net/rpc.py": {
        "RpcClient._call_loop":
            "the retry engine itself: every attempt re-checks the verb"
            " policy's end-to-end deadline, and the statement-level"
            " checkpoint discipline sits at the call sites above it",
    },
}

#: functions that COUNT as a checkpoint observation when reached from a
#: loop body's closure — (path, qualname); audited like CANCEL_EXEMPT
CHECKPOINT_EQUIV: set[tuple[str, str]] = set()

#: audited one-shot initializers whose bodies are NOT scanned for
#: blocking triggers: (path, qualname) -> why.  native._load runs
#: ``make`` exactly once per process (guarded by _build_attempted), so
#: the crc64 fast path that every digest loop rides is not a per-
#: iteration block.
CANCEL_NONBLOCKING: dict[tuple[str, str], str] = {
    ("oceanbase_tpu/native.py", "_load"):
        "lazy one-time native build: the subprocess runs at most once "
        "per process, after which the ctypes fast path is pure CPU",
}

#: receiver names whose .wait()/.is_set() is a cancellation observation
_STOPPISH = ("stop", "cancel", "kill", "shutdown", "quit")

_RPC_ATTRS = {"call", "call_with_size", "ping"}
_SUBPROCESS_FNS = {"run", "check_call", "check_output", "Popen", "call"}


def _scope_files(az: Analyzer) -> list[str]:
    return [p for p in az.trees
            if any(fnmatch.fnmatch(p, pat) for pat in CANCEL_SCOPE)]


def _is_blocking_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr in _RPC_ATTRS:
        d = dotted_name(f) or ""
        root = d.split(".")[0]
        if root in ("time", "os", "json", "struct"):
            return False  # stdlib namesakes, not an RpcClient
        return True
    d = dotted_name(f) or ""
    if d == "time.sleep":
        return True
    parts = d.split(".")
    if parts[0] == "shutil" and \
            parts[-1].startswith(("copy", "move")):
        return True
    if parts[0] == "subprocess" and parts[-1] in _SUBPROCESS_FNS:
        return True
    return False


def _imported_module(idx: _Index, path: str, name: str) -> str | None:
    """The full module a bare name refers to (``import m as name`` or
    ``from pkg import mod as name``), else None."""
    mod = idx.alias[path].get(name)
    if mod is not None:
        return mod
    imp = idx.from_imp[path].get(name)
    if imp is not None:
        return f"{imp[0]}.{imp[1]}"
    return None


def _is_checkpoint_call(idx: _Index, path: str, call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        if f.id != "checkpoint":
            return False
        imp = idx.from_imp[path].get("checkpoint")
        return imp is not None and imp[0] == ADMISSION_MODULE
    if isinstance(f, ast.Attribute) and f.attr == "checkpoint" and \
            isinstance(f.value, ast.Name):
        # qadmission.checkpoint() — NOT tenant.checkpoint() (the storage
        # replay-point flush shares the name); resolve via import maps
        return _imported_module(idx, path, f.value.id) == ADMISSION_MODULE
    return False


def _is_observation_call(idx: _Index, path: str, call: ast.Call) -> bool:
    if _is_checkpoint_call(idx, path, call):
        return True
    f = call.func
    if not isinstance(f, ast.Attribute):
        return False
    if f.attr == "check" and not call.args:
        recv = dotted_name(f.value) or ""
        last = recv.split(".")[-1].lower()
        return "ctx" in last or "stmt" in last
    if f.attr in ("wait", "is_set"):
        recv = (dotted_name(f.value) or "").lower()
        return any(s in recv for s in _STOPPISH)
    return False


def _resolve(idx: _Index, path: str, call: ast.Call
             ) -> list[tuple[str, str]]:
    out = idx.resolve_call(path, call)
    if out:
        return out
    f = call.func
    if isinstance(f, ast.Attribute):
        cands = [q for q in idx.by_name[path].get(f.attr, []) if "." in q]
        if 0 < len(cands) <= 2:
            return [(path, q) for q in cands]
    return []


def _walk_no_defs(node: ast.AST):
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _loop_scan(idx: _Index, path: str, loop: ast.AST
               ) -> tuple[bool, bool]:
    """(blocks, observes) over the loop's body closure: direct calls in
    the loop subtree plus the bodies of every package function they
    transitively reach."""
    calls = [n for n in _walk_no_defs(loop) if isinstance(n, ast.Call)]
    if isinstance(loop, ast.While):  # `while not stop.wait(t):`
        calls += [n for n in ast.walk(loop.test)
                  if isinstance(n, ast.Call)]
    blocks = observes = False
    seen: set[tuple[str, str]] = set()
    work: list[tuple[str, list[ast.Call]]] = [(path, calls)]
    while work and not (blocks and observes):
        p, cs = work.pop()
        for c in cs:
            if _is_blocking_call(c):
                blocks = True
            if _is_observation_call(idx, p, c):
                observes = True
            for tgt in _resolve(idx, p, c):
                if tgt in seen:
                    continue
                seen.add(tgt)
                if tgt in CHECKPOINT_EQUIV:
                    observes = True
                if tgt in CANCEL_NONBLOCKING:
                    continue
                info = idx.funcs.get(tgt)
                if info is not None:
                    work.append((info.path, info.calls))
    return blocks, observes


def _top_level_loops(fnode: ast.AST):
    """Outermost for/while loops of a function body (a checkpointed
    outer loop bounds its inner retry ladders per iteration).  A ``for``
    over a literal tuple/list is bounded by the source text (O(1)
    iterations) — skipped, but its body may still hold real loops."""
    stack = list(ast.iter_child_nodes(fnode))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        if isinstance(n, ast.For) and \
                isinstance(n.iter, (ast.Tuple, ast.List)):
            stack.extend(n.body)
            continue
        if isinstance(n, (ast.For, ast.While)):
            yield n
            continue
        stack.extend(ast.iter_child_nodes(n))


def _thread_fanouts(fnode: ast.AST) -> list[tuple[ast.Call, str]]:
    """(call, target_name) for Thread(target=X)/submit(X) sites that sit
    inside a loop or comprehension — a fan-out, not a lone daemon."""
    out: list[tuple[ast.Call, str]] = []

    def visit(node, in_loop):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef)):
                continue
            here = in_loop or isinstance(
                child, (ast.For, ast.While, ast.ListComp, ast.SetComp,
                        ast.GeneratorExp))
            if here and isinstance(child, ast.Call):
                d = dotted_name(child.func) or ""
                tgt = None
                if d.split(".")[-1] == "Thread":
                    for kw in child.keywords:
                        if kw.arg == "target" and \
                                isinstance(kw.value, ast.Name):
                            tgt = kw.value.id
                elif isinstance(child.func, ast.Attribute) and \
                        child.func.attr == "submit" and child.args and \
                        isinstance(child.args[0], ast.Name):
                    tgt = child.args[0].id
                if tgt is not None:
                    out.append((child, tgt))
            visit(child, here)

    visit(fnode, False)
    return out


def _closure_blocks_rpc(idx: _Index, root: tuple[str, str]) -> bool:
    seen = {root}
    work = [root]
    while work:
        info = idx.funcs.get(work.pop())
        if info is None:
            continue
        for c in info.calls:
            f = c.func
            if isinstance(f, ast.Attribute) and f.attr in _RPC_ATTRS:
                return True
            for tgt in _resolve(idx, info.path, c):
                if tgt not in seen:
                    seen.add(tgt)
                    work.append(tgt)
    return False


def _has_cancel_path(fnode: ast.AST) -> bool:
    """A cancellation-propagation path in the spawning function: a
    cancel-verb string constant anywhere in its closure-visible body
    (``cli.call("dtl.cancel", ...)``) or stop/cancel event plumbing."""
    for n in ast.walk(fnode):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) and \
                "cancel" in n.value:
            return True
        if isinstance(n, (ast.Name, ast.Attribute)):
            s = (n.id if isinstance(n, ast.Name) else n.attr).lower()
            if any(t in s for t in _STOPPISH):
                return True
    return False


def check_cancel_rules(az: Analyzer,
                       exempt: dict[str, dict[str, str]] | None = None
                       ) -> list[Finding]:
    exempt = CANCEL_EXEMPT if exempt is None else exempt
    idx = _Index(az)
    out: list[Finding] = []
    flagged_exempt: set[tuple[str, str]] = set()  # exempt fns that NEED it
    for path in _scope_files(az):
        for (p, qual), info in idx.funcs.items():
            if p != path:
                continue
            exempted = qual in exempt.get(p, {})
            for loop in _top_level_loops(info.node):
                blocks, observes = _loop_scan(idx, p, loop)
                if not blocks or observes:
                    continue
                if exempted:
                    flagged_exempt.add((p, qual))
                    continue
                out.append(Finding(
                    "cancel.loop-no-checkpoint", p, loop.lineno, qual,
                    "blocking loop (rpc/sleep/bulk-copy in its call "
                    "closure) never reaches admission.checkpoint(); a "
                    "KILL or statement deadline waits out the whole "
                    "transfer"))
            for call, tgt in _thread_fanouts(info.node):
                targets = [(p, q) for q in idx.by_name[p].get(tgt, [])]
                if not any(_closure_blocks_rpc(idx, t) for t in targets):
                    continue
                if _has_cancel_path(info.node):
                    continue
                out.append(Finding(
                    "cancel.fanout-no-propagation", p, call.lineno, qual,
                    f"RPC fan-out thread target {tgt!r} has no "
                    f"cancellation-propagation path (no cancel verb, no "
                    f"stop event) — in-flight remote work outlives a "
                    f"kill; see the dtl.cancel pattern"))
    # registry hygiene
    for path, entries in sorted(exempt.items()):
        if path not in az.trees:
            continue
        for qual in sorted(entries):
            key = (path, qual)
            if key not in idx.funcs:
                out.append(Finding(
                    "cancel.unknown-exempt", path, 1, qual,
                    f"CANCEL_EXEMPT names unknown function {qual!r} "
                    f"(renamed or removed? prune the entry)"))
            elif key not in flagged_exempt:
                out.append(Finding(
                    "cancel.stale-exempt", path,
                    idx.funcs[key].node.lineno, qual,
                    f"stale CANCEL_EXEMPT entry: {qual!r} has no "
                    f"unobserved blocking loop anymore (prune it)"))
    return out
