"""io-discipline checker: durable binary writes carry checksums
(rules ``io.*``).

The integrity plane's standing contract (ROADMAP, PR 9): every NEW
persistence boundary ships bytes with a crc64-family digest computed at
write time and re-verified on load.  The enforcement is reachability,
not ceremony: a function that opens a file in a binary *create* mode
(``"wb"``/``"xb"``) inside the durable surface (``storage/``,
``palf/``, ``net/``, ``server/``) must reach one of the
``storage/integrity.py`` digest helpers (``crc64``/``bytes_crc``/
``arrays_crc``/``chunk_crc``/``table_digest``) in its transitive call
closure — computing the digest it writes, or verifying the bytes it is
about to install (the rebuild/scrub staging pattern).

Transient-by-design artifacts (spill chunks, TLS PEMs whose loader is
the verifier) live in the audited ``IO_EXEMPT`` registry.  Rules:

- ``io.unverified-write``        — binary create-mode write with no
                                   digest helper in the writer's call
                                   closure, not registered, no pragma;
- ``io.inplace-durable-write``   — a create-mode ``open`` (binary OR
                                   text) in the durable surface that
                                   writes its final path directly: a
                                   crash mid-write leaves a TORN
                                   current-generation artifact.  The
                                   discipline is stage-then-publish —
                                   write a ``*.tmp`` sibling and
                                   ``os.replace`` it over the real name
                                   (append-mode writes are exempt: the
                                   unwind protocol truncates them back).
                                   Verified-staging writers live in the
                                   audited ``INPLACE_EXEMPT`` registry;
- ``io.unregistered-exemption``  — registry hygiene: an ``IO_EXEMPT``/
                                   ``INPLACE_EXEMPT`` entry naming a
                                   function that no longer exists
                                   (unknown) or one whose writes no
                                   longer trip the rule (stale) — the
                                   registries must not rot into
                                   suppression dumps.
"""

from __future__ import annotations

import ast
import fnmatch

from oceanbase_tpu.analysis.core import (
    Analyzer,
    Finding,
    dotted_name,
)
from oceanbase_tpu.analysis.trace_safety import _Index, _walk_own

#: the durable surface under contract (glob patterns over repo paths)
IO_SCOPE = (
    "oceanbase_tpu/storage/*.py",
    "oceanbase_tpu/palf/*.py",
    "oceanbase_tpu/net/*.py",
    "oceanbase_tpu/server/*.py",
)

#: storage/integrity.py digest helpers (plus the native crc64 they wrap)
DIGEST_HELPERS = {"crc64", "bytes_crc", "arrays_crc", "chunk_crc",
                  "table_digest"}

#: binary create modes under contract ("ab" appends ride an existing
#: format whose entries self-verify; text modes are config/docs)
WRITE_MODES = {"wb", "xb", "wb+", "xb+", "w+b", "x+b"}

#: every create mode (binary + text) — the in-place rule covers both:
#: a torn manifest.json is as fatal as a torn segment
CREATE_MODES = WRITE_MODES | {"w", "x", "w+", "x+", "wt", "xt"}

#: audited transient-by-design writers: path -> qualname -> why the
#: missing digest is correct.  The exemption documents the audit, it
#: does not waive review.
IO_EXEMPT: dict[str, dict[str, str]] = {
    "oceanbase_tpu/storage/tmpfile.py": {
        "TempFileStore.append_chunk":
            "spill chunks are transient per-statement artifacts: a torn"
            " or rotten chunk fails the statement on read-back"
            " (np.load raises), never durability",
    },
    "oceanbase_tpu/server/tls.py": {
        "ensure_server_credentials":
            "self-signed PEM pair: ssl.load_cert_chain is the"
            " verify-on-load (a corrupt PEM fails loudly at server"
            " start) and the pair is regenerated, not repaired",
    },
}

#: audited direct-path writers for io.inplace-durable-write: functions
#: whose create-mode writes are safe WITHOUT tmp+rename because the
#: destination is itself a staging/ephemeral artifact or is verified
#: before install.  path -> qualname -> why.
INPLACE_EXEMPT: dict[str, dict[str, str]] = {
    "oceanbase_tpu/storage/scrub.py": {
        "Scrubber._repair_from_peer":
            "writes the fetched manifest into the .scrub_tmp staging"
            " dir, which is rmtree'd and rebuilt per attempt; segments"
            " install from staging only after digest verification",
    },
    "oceanbase_tpu/net/rebuild.py": {
        "fetch_file":
            "rebuild/scrub staging download: every chunk is"
            " crc-verified before the write and the whole file against"
            " the peer digest after assembly — a torn dst is re-fetched"
            " wholesale, never trusted",
    },
    "oceanbase_tpu/server/tls.py": {
        "ensure_server_credentials":
            "self-signed PEM pair regenerated on any load failure:"
            " ssl.load_cert_chain verifies at server start and a torn"
            " PEM is replaced, not repaired",
    },
}


def _scope_files(az: Analyzer) -> list[str]:
    return [p for p in az.trees
            if any(fnmatch.fnmatch(p, pat) for pat in IO_SCOPE)]


def _write_mode(call: ast.Call) -> str | None:
    """The binary create mode of an ``open``/``os.fdopen`` call, else
    None."""
    d = dotted_name(call.func)
    if d not in ("open", "os.fdopen"):
        return None
    mode_node = call.args[1] if len(call.args) >= 2 else None
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if isinstance(mode_node, ast.Constant) and \
            isinstance(mode_node.value, str) and \
            mode_node.value in WRITE_MODES:
        return mode_node.value
    return None


def _create_mode(call: ast.Call) -> str | None:
    """The create mode (binary or text) of an ``open``/``os.fdopen``
    call, else None."""
    d = dotted_name(call.func)
    if d not in ("open", "os.fdopen"):
        return None
    mode_node = call.args[1] if len(call.args) >= 2 else None
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if isinstance(mode_node, ast.Constant) and \
            isinstance(mode_node.value, str) and \
            mode_node.value in CREATE_MODES:
        return mode_node.value
    return None


def _path_is_staged(call: ast.Call) -> bool:
    """True when the open's path expression visibly names a staging
    artifact: a ``*.tmp``-suffixed string, or a variable/attribute
    whose name contains ``tmp`` (``tmp``, ``tmp_path``, ``state_tmp``).
    Under-detection only ever over-reports into the audited registry,
    never silently passes a direct write."""
    node = call.args[0] if call.args else None
    if node is None:
        return False
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str) \
                and "tmp" in n.value.lower():
            return True
        if isinstance(n, ast.Name) and "tmp" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "tmp" in n.attr.lower():
            return True
    return False


def _publishes_by_rename(fnode: ast.AST) -> bool:
    """Does this function (own statements only) call os.replace /
    os.rename — i.e. stage-then-publish within the same frame?"""
    for n in _walk_own(fnode):
        if isinstance(n, ast.Call) and \
                dotted_name(n.func) in ("os.replace", "os.rename"):
            return True
    return False


def _resolve_with_methods(idx: _Index, path: str, call: ast.Call
                          ) -> list[tuple[str, str]]:
    """``_Index.resolve_call`` plus a file-local unique-method fallback:
    an attribute call on an unresolvable receiver (``e.encode()``)
    resolves to same-file methods of that name when the name is close to
    unique (≤2 candidates) — the lock_order heuristic.  Under-resolution
    only ever under-reports; the fallback keeps single-class files like
    palf/log.py (LogEntry.encode embeds the crc) honest."""
    out = idx.resolve_call(path, call)
    if out:
        return out
    f = call.func
    if isinstance(f, ast.Attribute):
        cands = [q for q in idx.by_name[path].get(f.attr, []) if "." in q]
        if 0 < len(cands) <= 2:
            return [(path, q) for q in cands]
    return []


def _closure(idx: _Index, root: tuple[str, str]) -> set[tuple[str, str]]:
    """Transitive call closure of one function (with the unique-method
    fallback), bounded by the package file set."""
    scope = {root}
    work = [root]
    while work:
        key = work.pop()
        info = idx.funcs.get(key)
        if info is None:
            continue
        for call in info.calls:
            for tgt in _resolve_with_methods(idx, info.path, call):
                if tgt not in scope:
                    scope.add(tgt)
                    work.append(tgt)
    return scope


def _mentions_digest(fnode: ast.AST) -> bool:
    for n in ast.walk(fnode):
        if isinstance(n, ast.Call):
            d = dotted_name(n.func) or ""
            if d.split(".")[-1] in DIGEST_HELPERS:
                return True
    return False


def _digest_protected(idx: _Index, key: tuple[str, str]) -> bool:
    for tgt in _closure(idx, key):
        info = idx.funcs.get(tgt)
        if info is not None and _mentions_digest(info.node):
            return True
    return False


def _binary_writes(info) -> list[tuple[ast.Call, str]]:
    # own-walk: a nested def's writes belong to its own _FuncInfo
    return [(n, m) for n in _walk_own(info.node)
            if isinstance(n, ast.Call) and (m := _write_mode(n))]


def check_io_rules(az: Analyzer,
                   exempt: dict[str, dict[str, str]] | None = None,
                   inplace_exempt: dict[str, dict[str, str]] | None = None
                   ) -> list[Finding]:
    exempt = IO_EXEMPT if exempt is None else exempt
    inplace_exempt = (INPLACE_EXEMPT if inplace_exempt is None
                      else inplace_exempt)
    idx = _Index(az)
    out: list[Finding] = []
    writers: dict[tuple[str, str], bool] = {}  # key -> protected?
    #: key -> has at least one direct-path create write (pre-exemption)
    inplace_writers: dict[tuple[str, str], bool] = {}
    for path in _scope_files(az):
        for (p, qual), info in idx.funcs.items():
            if p != path:
                continue
            creates = [(n, m) for n in _walk_own(info.node)
                       if isinstance(n, ast.Call)
                       and (m := _create_mode(n))]
            if creates:
                renames = _publishes_by_rename(info.node)
                direct = [(c, m) for c, m in creates
                          if not renames and not _path_is_staged(c)]
                inplace_writers[(p, qual)] = bool(direct)
                if qual not in inplace_exempt.get(p, {}):
                    for call, mode in direct:
                        out.append(Finding(
                            "io.inplace-durable-write", p, call.lineno,
                            qual,
                            f'create-mode write (mode "{mode}") lands '
                            f'on its final path: a crash mid-write '
                            f'tears the current generation — stage a '
                            f'*.tmp sibling and os.replace it, or '
                            f'register in io_rules.INPLACE_EXEMPT'))
            writes = _binary_writes(info)
            if not writes:
                continue
            protected = _digest_protected(idx, (p, qual))
            writers[(p, qual)] = protected
            if protected or qual in exempt.get(p, {}):
                continue
            for call, mode in writes:
                out.append(Finding(
                    "io.unverified-write", p, call.lineno, qual,
                    f'binary write (mode "{mode}") lacks a reachable '
                    f'storage/integrity digest (crc on write or '
                    f'verify-on-load); route through integrity helpers '
                    f'or register in io_rules.IO_EXEMPT'))
    # registry hygiene (only for paths present in the analyzed set, so
    # synthetic test trees never trip over the real repo's entries)
    for path, entries in sorted(exempt.items()):
        if path not in az.trees:
            continue
        for qual in sorted(entries):
            key = (path, qual)
            if key not in idx.funcs:
                out.append(Finding(
                    "io.unregistered-exemption", path, 1, qual,
                    f"IO_EXEMPT names unknown function {qual!r} "
                    f"(renamed or removed? prune the entry)"))
            elif key not in writers or writers[key]:
                out.append(Finding(
                    "io.unregistered-exemption", path,
                    idx.funcs[key].node.lineno, qual,
                    f"stale IO_EXEMPT entry: {qual!r} has no "
                    f"unverified binary write anymore (prune it)"))
    for path, entries in sorted(inplace_exempt.items()):
        if path not in az.trees:
            continue
        for qual in sorted(entries):
            key = (path, qual)
            if key not in idx.funcs:
                out.append(Finding(
                    "io.unregistered-exemption", path, 1, qual,
                    f"INPLACE_EXEMPT names unknown function {qual!r} "
                    f"(renamed or removed? prune the entry)"))
            elif not inplace_writers.get(key, False):
                out.append(Finding(
                    "io.unregistered-exemption", path,
                    idx.funcs[key].node.lineno, qual,
                    f"stale INPLACE_EXEMPT entry: {qual!r} has no "
                    f"direct-path create write anymore (prune it)"))
    return out
