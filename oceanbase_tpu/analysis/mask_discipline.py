"""mask-discipline checker: operators must honor the live-row mask
(rules ``mask.*``).

The Static-shape policy (ROADMAP) keeps pad lanes dead in ``mask`` —
every function that reads ``Relation``/``Column`` payload data must
either consume the mask (gate lanes on it) or propagate it to its
output; a function that reads ``.data`` and ignores ``mask`` is exactly
the bug class that turns pad lanes into phantom rows.

The contract is explicit: ``OPERATOR_MODULES`` names the operator
surface, ``CONTRACTS`` registers audited exceptions (helpers whose mask
handling is their caller's documented responsibility).  Rules:

- ``mask.drop``          — reads Relation/Column data, never touches
                           mask, not registered, no pragma;
- ``mask.stale-exempt``  — registered exemption for a function that now
                           handles mask itself (the registry must not
                           rot into a suppression dump);
- ``mask.unknown-exempt``— registry entry naming a function that no
                           longer exists.
"""

from __future__ import annotations

import ast
import fnmatch

from oceanbase_tpu.analysis.core import (
    Analyzer,
    Finding,
    attrs_in,
    dotted_name,
    iter_functions,
)

# the operator surface under contract (glob patterns over repo paths)
OPERATOR_MODULES = (
    "oceanbase_tpu/exec/ops.py",
    "oceanbase_tpu/exec/window.py",
    "oceanbase_tpu/px/*.py",
)

# audited exceptions: qualname (per file) -> why the missing mask touch
# is correct.  These are helpers whose *caller* owns the mask contract —
# the exemption documents the audit, it does not waive review.
CONTRACTS: dict[str, dict[str, str]] = {
    "oceanbase_tpu/exec/ops.py": {
        "_combined_key": "key mixer; callers gate matches via _keys_valid"
                         " which folds the caller's mask",
        "_translate_dict": "code remap on static dictionaries; validity/"
                           "mask stay with the caller's columns",
        "_concat_valid": "validity-lane helper; concat() concatenates the"
                         " masks itself",
    },
    "oceanbase_tpu/exec/window.py": {},
    "oceanbase_tpu/px/exchange.py": {
        "_hash_dest": "dest vector; all_to_all_repartition masks dead"
                      " rows to the drop sentinel",
    },
    "oceanbase_tpu/px/range_sort.py": {
        "_primary_scalar": "key scalarizer; dist_sort_shard masks dead"
                           " rows to the drop destination",
    },
    "oceanbase_tpu/px/planner.py": {
        "_row_bytes": "static bytes-per-row estimate from dtype metadata",
        "_keys_hash_partitionable": "plan-time type probe: reads dtypes "
                                    "via eval_expr to pick a dist "
                                    "strategy, emits no row data",
    },
    "oceanbase_tpu/px/dtl.py": {},
    "oceanbase_tpu/px/bloom.py": {
        "_hashes": "returns a NULL-folded validity lane; build/apply "
                   "AND it with the relation mask",
    },
    "oceanbase_tpu/px/dist_ops.py": {},
}

# reading payload: any of these attribute accesses / calls
_DATA_ATTRS = {"data", "valid"}
_DATA_CALLS = {"eval_expr", "eval_predicate"}
# touching the mask contract: any of these
_MASK_ATTRS = {"mask"}
_MASK_CALLS = {"mask_or_true", "with_mask", "filter_rows", "compact"}
_MASK_PARAMS = {"mask", "live", "weight", "m"}


def _reads_data(fnode: ast.AST) -> bool:
    if _DATA_ATTRS & attrs_in(fnode):
        return True
    for n in ast.walk(fnode):
        if isinstance(n, ast.Call):
            d = dotted_name(n.func) or ""
            if d.split(".")[-1] in _DATA_CALLS:
                return True
    return False


def _touches_mask(fnode: ast.AST) -> bool:
    if _MASK_ATTRS & attrs_in(fnode):
        return True
    for n in ast.walk(fnode):
        if isinstance(n, ast.Call):
            d = dotted_name(n.func) or ""
            if d.split(".")[-1] in _MASK_CALLS:
                return True
        if isinstance(n, ast.keyword) and n.arg in _MASK_PARAMS:
            return True
    args = getattr(fnode, "args", None)
    if args is not None:
        params = {a.arg for a in
                  args.posonlyargs + args.args + args.kwonlyargs}
        if params & _MASK_PARAMS:
            return True
    return False


def _operator_files(az: Analyzer) -> list[str]:
    out = []
    for path in az.trees:
        if any(fnmatch.fnmatch(path, pat) for pat in OPERATOR_MODULES):
            out.append(path)
    return sorted(out)


def check_mask_discipline(az: Analyzer) -> list[Finding]:
    out: list[Finding] = []
    for path in _operator_files(az):
        tree = az.trees[path]
        exempt = CONTRACTS.get(path, {})
        seen: set[str] = set()
        for qual, fnode, _cls in iter_functions(tree):
            seen.add(qual)
            reads = _reads_data(fnode)
            touches = _touches_mask(fnode)
            if qual in exempt:
                if not reads or touches:
                    out.append(Finding(
                        "mask.stale-exempt", path, fnode.lineno, qual,
                        f"registry exempts {qual} but it "
                        f"{'does not read data' if not reads else 'already handles mask'}"
                        f" — drop the stale entry"))
                continue
            if reads and not touches:
                out.append(Finding(
                    "mask.drop", path, fnode.lineno, qual,
                    f"{qual} reads Relation/Column data but neither "
                    f"consumes nor propagates mask — pad lanes would "
                    f"leak into results"))
        for name in exempt:
            if name not in seen:
                out.append(Finding(
                    "mask.unknown-exempt", path, 1, name,
                    f"registry exempts unknown function {name}"))
    return out
