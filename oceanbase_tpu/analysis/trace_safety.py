"""trace-safety checker: host syncs and retrace hazards (rules ``trace.*``).

The static-shape policy (ROADMAP) only pays off while compiled plans are
actually reused, and reuse dies two ways:

- **host syncs** — ``int()/float()/bool()/.item()/np.asarray`` applied
  to a device value blocks the host on the XLA stream (inside a traced
  body it is worse: a ``ConcretizationError`` or a silently baked-in
  constant).  Rule ``trace.host-sync``.
- **retrace hazards** — Python ``if``/``while`` on a tracer-derived
  value (``trace.tracer-branch``) and identity-hashed or mutable objects
  in compile-cache keys (``trace.cache-key``): ``lru_cache`` keyed on an
  object without content ``__hash__``/``__eq__`` mints a fresh XLA
  executable per instance even when nothing changed.

Scope is computed, not declared: traced roots are functions passed to
``jax.jit``/``shard_map`` (or decorated with them); the *device scope*
is their transitive call closure.  The *host half* is tracked by a small
intraprocedural taint: names bound to jit-compiled callables (directly
or via a factory that returns one) mark their call results as device
values, so ``out, ovf = run(x); int(ovf)`` is flagged in the caller even
though the caller itself is never traced.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from oceanbase_tpu.analysis.core import (
    Analyzer,
    Finding,
    dotted_name,
    iter_functions,
)

# call names that trace their function argument
JIT_NAMES = {"jit", "shard_map", "pmap", "shard_map_compat"}
# numpy module aliases whose asarray/array force device->host transfer
NP_ALIASES = {"np", "numpy"}
SYNC_BUILTINS = {"int", "float", "bool"}
# an argument mentioning any of these is static/aux metadata, not data
STATIC_MARKERS = {
    "shape", "ndim", "size", "itemsize", "capacity", "sdict", "values",
    "scale", "precision", "dtype", "np_dtype", "kind", "len", "math",
    "iinfo", "finfo", "axis_names", "devices", "device_count", "fields",
    "maxsize", "environ", "time", "monotonic", "perf_counter",
}
# tracer-producing call prefixes (first segment of the dotted name)
TRACER_ROOTS = {"jnp", "lax"}
TRACER_DOTTED_PREFIXES = ("jax.lax.", "jax.ops.", "jax.numpy.", "jnp.",
                          "lax.")


def _module_of(path: str) -> str:
    mod = path[:-3] if path.endswith(".py") else path
    if mod.endswith("/__init__"):
        mod = mod[: -len("/__init__")]
    return mod.replace("/", ".")


@dataclass
class _FuncInfo:
    path: str
    qual: str
    node: ast.AST
    cls: str | None
    calls: list[ast.Call] = field(default_factory=list)


class _Index:
    """Cross-file function/class/import index with best-effort call
    resolution (precise enough for reachability, never raising)."""

    def __init__(self, az: Analyzer):
        self.az = az
        self.funcs: dict[tuple[str, str], _FuncInfo] = {}
        self.by_name: dict[str, dict[str, list[str]]] = {}  # path->name->quals
        self.classes: dict[str, dict[str, ast.ClassDef]] = {}
        self.mod_to_path = {_module_of(p): p for p in az.trees}
        # per-path import maps (module level + function local, merged)
        self.alias: dict[str, dict[str, str]] = {}       # alias -> module
        self.from_imp: dict[str, dict[str, tuple[str, str]]] = {}
        for path, tree in az.trees.items():
            self.classes[path] = {
                n.name: n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef)}
            al: dict[str, str] = {}
            fi: dict[str, tuple[str, str]] = {}
            for n in ast.walk(tree):
                if isinstance(n, ast.Import):
                    for a in n.names:
                        al[a.asname or a.name.split(".")[0]] = a.name
                elif isinstance(n, ast.ImportFrom) and n.module:
                    for a in n.names:
                        fi[a.asname or a.name] = (n.module, a.name)
            self.alias[path] = al
            self.from_imp[path] = fi
            names: dict[str, list[str]] = {}
            for qual, fnode, cls in iter_functions(tree):
                info = _FuncInfo(path, qual, fnode, cls)
                info.calls = [c for c in ast.walk(fnode)
                              if isinstance(c, ast.Call)]
                self.funcs[(path, qual)] = info
                names.setdefault(qual.split(".")[-1], []).append(qual)
            self.by_name[path] = names

    # -- resolution ------------------------------------------------------
    def resolve_call(self, path: str, call: ast.Call
                     ) -> list[tuple[str, str]]:
        """Call node -> candidate (path, qualname) targets in the file
        set.  Bare names resolve in-module then via from-imports; dotted
        ``mod.fn`` resolves only through known module aliases; ``self.m``
        resolves within the enclosing class's file."""
        f = call.func
        if isinstance(f, ast.Name):
            return self._resolve_name(path, f.id)
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls"):
                    # any method of this name in the same file (class
                    # attribution is approximate but file-local)
                    return [(path, q)
                            for q in self.by_name[path].get(f.attr, [])
                            if "." in q]
                mod = self.alias[path].get(base.id)
                if mod is None and base.id in self.from_imp[path]:
                    src_mod, orig = self.from_imp[path][base.id]
                    mod = f"{src_mod}.{orig}"
                if mod is not None:
                    tp = self.mod_to_path.get(mod) or self.mod_to_path.get(
                        mod + ".__init__")
                    if tp is not None:
                        return [(tp, q)
                                for q in self.by_name[tp].get(f.attr, [])]
                    return []  # external module: not ours
            # unknown receiver: unresolved (keeps the scope tight)
            return []
        return []

    def _resolve_name(self, path: str, name: str) -> list[tuple[str, str]]:
        out = [(path, q) for q in self.by_name[path].get(name, [])]
        if out:
            return out
        imp = self.from_imp[path].get(name)
        if imp is not None:
            mod, orig = imp
            tp = self.mod_to_path.get(mod) or self.mod_to_path.get(
                mod + ".__init__")
            if tp is not None:
                return [(tp, q) for q in self.by_name[tp].get(orig, [])]
        return []


def _is_jit_call(call: ast.Call) -> bool:
    d = dotted_name(call.func)
    return d is not None and d.split(".")[-1] in JIT_NAMES


def _has_jit_decorator(fnode) -> bool:
    for dec in getattr(fnode, "decorator_list", []):
        d = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
        if d and d.split(".")[-1] in JIT_NAMES:
            return True
        if isinstance(dec, ast.Call):  # functools.partial(jax.jit, ...)
            for a in dec.args:
                ad = dotted_name(a)
                if ad and ad.split(".")[-1] in JIT_NAMES:
                    return True
    return False


def _traced_roots(idx: _Index) -> set[tuple[str, str]]:
    roots: set[tuple[str, str]] = set()
    for (path, qual), info in idx.funcs.items():
        if _has_jit_decorator(info.node):
            roots.add((path, qual))
    # functions passed (positionally) to jit/shard_map call sites
    for (path, _qual), info in idx.funcs.items():
        for call in info.calls:
            if not _is_jit_call(call):
                continue
            for a in call.args[:1]:  # the traced callable is arg 0
                if isinstance(a, ast.Name):
                    roots.update(idx._resolve_name(path, a.id))
                elif isinstance(a, ast.Call) and _is_jit_call(a):
                    for inner in a.args[:1]:
                        if isinstance(inner, ast.Name):
                            roots.update(
                                idx._resolve_name(path, inner.id))
    # module-level jit calls (outside any def)
    for path, tree in idx.az.trees.items():
        for n in ast.walk(tree):
            if isinstance(n, ast.Call) and _is_jit_call(n):
                for a in n.args[:1]:
                    if isinstance(a, ast.Name):
                        roots.update(idx._resolve_name(path, a.id))
    return roots


def _device_scope(idx: _Index,
                  roots: set[tuple[str, str]]) -> set[tuple[str, str]]:
    """Transitive call closure of the traced roots."""
    scope = set(roots)
    work = list(roots)
    while work:
        key = work.pop()
        info = idx.funcs.get(key)
        if info is None:
            continue
        for call in info.calls:
            for tgt in idx.resolve_call(info.path, call):
                if tgt not in scope:
                    scope.add(tgt)
                    work.append(tgt)
    return scope


# ---------------------------------------------------------------------------
# host-half taint: jit factories and their call results
# ---------------------------------------------------------------------------


def _returns_jit(info: _FuncInfo, idx: _Index) -> bool:
    """Does this function return a jit-compiled callable (directly, via a
    local name, or inside a returned tuple)?"""
    local_jit: set[str] = set()
    for n in ast.walk(info.node):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call) \
                and _is_jit_call(n.value):
            for t in n.targets:
                if isinstance(t, ast.Name):
                    local_jit.add(t.id)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and n is not info.node and _has_jit_decorator(n):
            local_jit.add(n.name)
    for n in ast.walk(info.node):
        if not isinstance(n, ast.Return) or n.value is None:
            continue
        vals = n.value.elts if isinstance(n.value, ast.Tuple) else [n.value]
        for v in vals:
            if isinstance(v, ast.Call) and _is_jit_call(v):
                return True
            if isinstance(v, ast.Name) and v.id in local_jit:
                return True
    return False


def _refs(node: ast.AST, tainted: set[str]) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and n.id in tainted:
            return True
    return False


def _target_names(t: ast.AST) -> list[str]:
    if isinstance(t, ast.Name):
        return [t.id]
    if isinstance(t, (ast.Tuple, ast.List)):
        out: list[str] = []
        for e in t.elts:
            out.extend(_target_names(e))
        return out
    return []


def _host_device_names(info: _FuncInfo, idx: _Index,
                       factories: set[tuple[str, str]]) -> set[str]:
    """Names holding device values in a host function: results of calling
    a jitted callable (bound from ``jax.jit(...)`` or a factory)."""
    jit_callables: set[str] = set()
    device: set[str] = set()
    for _ in range(3):  # tiny fixpoint: assignment chains are short
        for n in ast.walk(info.node):
            if isinstance(n, ast.Assign):
                v, tgts = n.value, n.targets
            elif isinstance(n, ast.AnnAssign) and n.value is not None:
                v, tgts = n.value, [n.target]
            elif isinstance(n, ast.For):
                if _refs(n.iter, device):
                    device.update(_target_names(n.target))
                continue
            elif isinstance(n, (ast.ListComp, ast.SetComp, ast.DictComp,
                                ast.GeneratorExp)):
                for gen in n.generators:
                    if _refs(gen.iter, device):
                        device.update(_target_names(gen.target))
                continue
            else:
                continue
            names = [x for t in tgts for x in _target_names(t)]
            if isinstance(v, ast.Call):
                if _is_jit_call(v):
                    jit_callables.update(names)
                    continue
                resolved = idx.resolve_call(info.path, v)
                if resolved and all(r in factories for r in resolved):
                    jit_callables.update(names)
                    continue
                fn = v.func
                if isinstance(fn, ast.Name) and fn.id in jit_callables:
                    device.update(names)
                    continue
            if _refs(v, device):
                device.update(names)
    return device


# ---------------------------------------------------------------------------
# flagging
# ---------------------------------------------------------------------------


def _mentions_static(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in STATIC_MARKERS:
            return True
        if isinstance(n, ast.Name) and n.id in STATIC_MARKERS:
            return True
    return False


def _int_annotated_params(fnode) -> set[str]:
    """Parameters annotated as plain python scalars are host values."""
    out = set()
    args = fnode.args
    for a in (args.posonlyargs + args.args + args.kwonlyargs):
        ann = a.annotation
        s = ast.unparse(ann) if ann is not None else ""
        if s in ("int", "float", "bool", "str",
                 "int | None", "float | None", "bool | None"):
            out.add(a.arg)
    return out


def _tracer_names(fnode) -> set[str]:
    """Names assigned from jnp./jax.lax./jax.ops. calls in a traced
    function body — Python branching on them is a retrace (or a
    concretization error)."""
    out: set[str] = set()
    for n in _walk_own(fnode):
        if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
            d = dotted_name(n.value.func) or ""
            if d.split(".")[0] in TRACER_ROOTS or \
                    d.startswith(TRACER_DOTTED_PREFIXES):
                for t in n.targets:
                    out.update(_target_names(t))
    return out


def _is_tracer_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    d = dotted_name(node.func) or ""
    return d.split(".")[0] in TRACER_ROOTS or \
        d.startswith(TRACER_DOTTED_PREFIXES)


def _walk_own(fnode):
    """Walk a function body WITHOUT descending into nested defs/classes
    (those are separate analysis units; descending double-flags)."""
    stack = list(ast.iter_child_nodes(fnode))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _device_evidence(node: ast.AST, tracers: set[str]) -> bool:
    """Does the expression plausibly reference device data — a
    tracer-derived name or a ``.data``/``.mask``/``.valid`` payload
    attribute?  (Static aux metadata like ``.dtype``/``.shape``/
    ``.sdict`` exempts the expression: trace-time host work on python
    scalars is the package's bread and butter, not a sync.)"""
    if _mentions_static(node):
        return False
    if _refs(node, tracers):
        return True
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("data", "mask",
                                                       "valid"):
            return True
    return False


def _flag_device_scope(info: _FuncInfo, az: Analyzer,
                       out: list[Finding]) -> None:
    fnode = info.node
    host_params = _int_annotated_params(fnode)
    tracers = _tracer_names(fnode)

    for n in _walk_own(fnode):
        # nested defs are visited as their own _FuncInfo
        if isinstance(n, ast.Call):
            d = dotted_name(n.func)
            if isinstance(n.func, ast.Name) and \
                    n.func.id in SYNC_BUILTINS and n.args:
                a = n.args[0]
                if isinstance(a, ast.Constant) or \
                        (isinstance(a, ast.Name) and a.id in host_params):
                    continue
                if not _device_evidence(a, tracers):
                    continue
                out.append(Finding(
                    "trace.host-sync", info.path, n.lineno, info.qual,
                    f"{n.func.id}({ast.unparse(a)}) in jit-reachable "
                    f"code forces a host sync (or concretizes a tracer)"))
            elif isinstance(n.func, ast.Attribute) and \
                    n.func.attr in ("item", "tolist") and not n.args:
                v = n.func.value
                param_ref = any(
                    isinstance(x, ast.Name) and x.id in {
                        a.arg for a in (fnode.args.posonlyargs
                                        + fnode.args.args
                                        + fnode.args.kwonlyargs)}
                    for x in ast.walk(v))
                if not (_device_evidence(v, tracers) or param_ref):
                    continue
                out.append(Finding(
                    "trace.host-sync", info.path, n.lineno, info.qual,
                    f".{n.func.attr}() on "
                    f"{ast.unparse(v)} in jit-reachable code"))
            elif d is not None and d.split(".")[0] in NP_ALIASES and \
                    d.split(".")[-1] in ("asarray", "array") and n.args:
                a = n.args[0]
                src = ast.unparse(a)
                # the dict-LUT idiom (host work on static aux metadata at
                # trace time) is legitimate; flag only device-data pulls
                if any(m in src for m in (".data", ".mask", ".valid")) \
                        and ".sdict" not in src and ".values" not in src:
                    out.append(Finding(
                        "trace.host-sync", info.path, n.lineno, info.qual,
                        f"{d}({src}) pulls device data to host in "
                        f"jit-reachable code"))
        elif isinstance(n, (ast.If, ast.While)):
            test = n.test
            if _mentions_static(test):
                continue  # dtype/shape branches resolve at trace time
            if _refs(test, tracers) or any(
                    _is_tracer_call(c) for c in ast.walk(test)):
                out.append(Finding(
                    "trace.tracer-branch", info.path, n.lineno, info.qual,
                    f"python branch on tracer-derived value "
                    f"({ast.unparse(test)[:60]}) retraces per outcome"))


def _flag_host_half(info: _FuncInfo, idx: _Index,
                    factories: set[tuple[str, str]],
                    out: list[Finding]) -> None:
    device = _host_device_names(info, idx, factories)
    if not device:
        return
    for n in _walk_own(info.node):
        if not isinstance(n, ast.Call):
            continue
        d = dotted_name(n.func)
        if isinstance(n.func, ast.Name) and n.func.id in SYNC_BUILTINS \
                and n.args and _refs(n.args[0], device):
            out.append(Finding(
                "trace.host-sync", info.path, n.lineno, info.qual,
                f"{n.func.id}({ast.unparse(n.args[0])}) blocks on the "
                f"XLA stream (device value from a jitted call)"))
        elif isinstance(n.func, ast.Attribute) and \
                n.func.attr in ("item", "tolist") and \
                _refs(n.func.value, device):
            out.append(Finding(
                "trace.host-sync", info.path, n.lineno, info.qual,
                f".{n.func.attr}() on {ast.unparse(n.func.value)} "
                f"blocks on the XLA stream"))
        elif d is not None and d.split(".")[0] in NP_ALIASES and \
                d.split(".")[-1] in ("asarray", "array") and n.args and \
                _refs(n.args[0], device):
            out.append(Finding(
                "trace.host-sync", info.path, n.lineno, info.qual,
                f"{d}({ast.unparse(n.args[0])}) blocks on the XLA "
                f"stream (device value from a jitted call)"))


# ---------------------------------------------------------------------------
# cache keys
# ---------------------------------------------------------------------------

_CACHE_DECOS = ("lru_cache", "cache")


def _cached_funcs(idx: _Index) -> set[tuple[str, str]]:
    out = set()
    for key, info in idx.funcs.items():
        for dec in getattr(info.node, "decorator_list", []):
            d = dotted_name(dec if not isinstance(dec, ast.Call)
                            else dec.func)
            if d and d.split(".")[-1] in _CACHE_DECOS:
                out.add(key)
    return out


def _class_hash_eq(cnode: ast.ClassDef) -> tuple[bool, bool]:
    """(has content __hash__, has content __eq__) — frozen dataclasses
    synthesize both."""
    names = {n.name for n in cnode.body
             if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
    frozen = False
    for dec in cnode.decorator_list:
        if isinstance(dec, ast.Call) and \
                (dotted_name(dec.func) or "").endswith("dataclass"):
            for kw in dec.keywords:
                if kw.arg == "frozen" and \
                        isinstance(kw.value, ast.Constant) and kw.value.value:
                    frozen = True
    return ("__hash__" in names or frozen, "__eq__" in names or frozen)


def _flag_cache_keys(idx: _Index, cached: set[tuple[str, str]],
                     out: list[Finding]) -> None:
    all_classes: dict[str, ast.ClassDef] = {}
    for path, cmap in idx.classes.items():
        all_classes.update(cmap)
    for (path, _qual), info in idx.funcs.items():
        for call in info.calls:
            resolved = idx.resolve_call(path, call)
            if not resolved or not any(r in cached for r in resolved):
                continue
            for a in list(call.args) + [kw.value for kw in call.keywords]:
                if isinstance(a, (ast.List, ast.Dict, ast.Set)):
                    out.append(Finding(
                        "trace.cache-key", path, a.lineno, info.qual,
                        f"mutable {type(a).__name__.lower()} literal in a "
                        f"compile-cache key (unhashable or identity-keyed)"))
                elif isinstance(a, ast.Call):
                    d = dotted_name(a.func)
                    if d == "id" or (d or "").endswith(".id"):
                        out.append(Finding(
                            "trace.cache-key", path, a.lineno, info.qual,
                            "id() in a compile-cache key is identity-"
                            "hashed: equal content still retraces"))
                        continue
                    cname = (d or "").split(".")[-1]
                    cnode = all_classes.get(cname)
                    if cnode is not None:
                        has_h, has_e = _class_hash_eq(cnode)
                        if not (has_h and has_e):
                            out.append(Finding(
                                "trace.cache-key", path, a.lineno,
                                info.qual,
                                f"{cname} lacks content __hash__/__eq__ "
                                f"but keys a compile cache: every "
                                f"instance mints a fresh executable"))


# ---------------------------------------------------------------------------
# entry
# ---------------------------------------------------------------------------


def check_trace_safety(az: Analyzer) -> list[Finding]:
    idx = _Index(az)
    roots = _traced_roots(idx)
    scope = _device_scope(idx, roots)
    factories = {key for key, info in idx.funcs.items()
                 if _returns_jit(info, idx)}
    out: list[Finding] = []
    for key, info in idx.funcs.items():
        if key in scope:
            _flag_device_scope(info, az, out)
        else:
            _flag_host_half(info, idx, factories, out)
    _flag_cache_keys(idx, _cached_funcs(idx), out)
    return out
