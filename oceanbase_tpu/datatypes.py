"""SQL type system mapped onto TPU-friendly physical dtypes.

Reference analog: the datum/vector type-class system
(src/share/vector/ob_vector_define.h:26-78 VecValueTypeClass,
src/share/datum/ob_datum.h).  The TPU build collapses the reference's ~40
type classes onto a small set of device representations:

- integers            -> int64 device arrays
- DECIMAL(p, s)       -> int64 device arrays scaled by 10**s (exact arithmetic;
                         reference keeps decimals as int32/64/128/256 "DEC_INT"
                         columns for the same reason)
- DATE                -> int32 days since 1970-01-01
- DATETIME/TIMESTAMP  -> int64 microseconds since epoch
- FLOAT/DOUBLE        -> float32/float64
- BOOL                -> bool
- CHAR/VARCHAR/TEXT   -> int32 dictionary codes into an order-preserving
                         host-side dictionary (sorted unique values), so
                         <, <=, = on codes match string collation order.
                         (reference: dict encoding in
                         src/storage/blocksstable/cs_encoding + VEC_DISCRETE)

NULLs are carried as a separate validity bitmap per column, like the
reference's null bitmaps (src/share/vector/ob_bitmap_null_vector_base.h).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import numpy as np


class TypeKind(enum.Enum):
    BOOL = "bool"
    INT = "int"            # all MySQL int widths collapse to i64
    DECIMAL = "decimal"
    FLOAT = "float"        # float32
    DOUBLE = "double"      # float64
    DATE = "date"
    DATETIME = "datetime"
    STRING = "string"
    VECTOR = "vector"      # fixed-dim float32 embedding (precision = dim)
    NULLTYPE = "null"      # type of the bare NULL literal


@dataclass(frozen=True)
class SqlType:
    """A resolved SQL type: kind + (precision, scale) for decimals.

    ``scale`` is the power-of-ten fixed-point scale for DECIMAL; 0 otherwise.
    """

    kind: TypeKind
    precision: int = 0
    scale: int = 0
    nullable: bool = True

    # ---- constructors -------------------------------------------------
    @staticmethod
    def int_() -> "SqlType":
        return SqlType(TypeKind.INT)

    @staticmethod
    def bool_() -> "SqlType":
        return SqlType(TypeKind.BOOL)

    @staticmethod
    def decimal(precision: int = 15, scale: int = 2) -> "SqlType":
        return SqlType(TypeKind.DECIMAL, precision, scale)

    @staticmethod
    def double() -> "SqlType":
        return SqlType(TypeKind.DOUBLE)

    @staticmethod
    def float_() -> "SqlType":
        return SqlType(TypeKind.FLOAT)

    @staticmethod
    def date() -> "SqlType":
        return SqlType(TypeKind.DATE)

    @staticmethod
    def datetime() -> "SqlType":
        return SqlType(TypeKind.DATETIME)

    @staticmethod
    def string() -> "SqlType":
        return SqlType(TypeKind.STRING)

    @staticmethod
    def vector(dim: int) -> "SqlType":
        """VECTOR(dim): per-row float32 embedding, Column.data [n, dim]
        (≙ the vector data type feeding src/share/vector_index)."""
        return SqlType(TypeKind.VECTOR, dim)

    @staticmethod
    def null() -> "SqlType":
        return SqlType(TypeKind.NULLTYPE)

    # ---- physical layout ----------------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        return {
            TypeKind.BOOL: np.dtype(np.bool_),
            TypeKind.INT: np.dtype(np.int64),
            TypeKind.DECIMAL: np.dtype(np.int64),
            TypeKind.FLOAT: np.dtype(np.float32),
            TypeKind.DOUBLE: np.dtype(np.float64),
            TypeKind.DATE: np.dtype(np.int32),
            TypeKind.DATETIME: np.dtype(np.int64),
            TypeKind.STRING: np.dtype(np.int32),   # dictionary codes
            TypeKind.VECTOR: np.dtype(np.float32),
            TypeKind.NULLTYPE: np.dtype(np.int64),
        }[self.kind]

    @property
    def is_numeric(self) -> bool:
        return self.kind in (
            TypeKind.INT,
            TypeKind.DECIMAL,
            TypeKind.FLOAT,
            TypeKind.DOUBLE,
        )

    @property
    def is_string(self) -> bool:
        return self.kind == TypeKind.STRING

    def __str__(self) -> str:  # pragma: no cover - debug aid
        if self.kind == TypeKind.DECIMAL:
            return f"DECIMAL({self.precision},{self.scale})"
        return self.kind.name


# ---------------------------------------------------------------------------
# Type arithmetic (result-type inference used by the resolver / expr engine).
# Mirrors the spirit of the reference's type deduction in expr resolution
# (src/sql/resolver/expr, src/sql/engine/expr ob_expr_*.cpp calc-type logic),
# simplified to the collapsed physical types above.
# ---------------------------------------------------------------------------

_NUM_RANK = {
    TypeKind.INT: 0,
    TypeKind.DECIMAL: 1,
    TypeKind.FLOAT: 2,
    TypeKind.DOUBLE: 3,
}


def common_numeric(a: SqlType, b: SqlType) -> SqlType:
    """Common supertype for binary arithmetic / comparison of numerics."""
    if a.kind == TypeKind.NULLTYPE:
        return b
    if b.kind == TypeKind.NULLTYPE:
        return a
    ra, rb = _NUM_RANK[a.kind], _NUM_RANK[b.kind]
    hi = a if ra >= rb else b
    if hi.kind == TypeKind.DECIMAL:
        scale = max(a.scale, b.scale)
        return SqlType(TypeKind.DECIMAL, max(a.precision, b.precision), scale)
    return SqlType(hi.kind)


def add_result(a: SqlType, b: SqlType) -> SqlType:
    return common_numeric(a, b)


def mul_result(a: SqlType, b: SqlType) -> SqlType:
    c = common_numeric(a, b)
    if c.kind == TypeKind.DECIMAL:
        # exact: scales add under multiplication of scaled ints
        return SqlType(TypeKind.DECIMAL, a.precision + b.precision, a.scale + b.scale)
    return c


def div_result(a: SqlType, b: SqlType) -> SqlType:
    # MySQL: decimal division increases scale; we return DOUBLE for the
    # device plane (exact decimal division deferred to a later round).
    c = common_numeric(a, b)
    if c.kind in (TypeKind.DECIMAL, TypeKind.INT):
        return SqlType(TypeKind.DOUBLE)
    return c


DATE_EPOCH = np.datetime64("1970-01-01", "D")


def date_to_days(s: str) -> int:
    """'1994-01-01' -> int32 days since epoch."""
    return int((np.datetime64(s, "D") - DATE_EPOCH).astype(np.int64))


def days_to_date(d: int) -> str:
    return str(DATE_EPOCH + np.timedelta64(int(d), "D"))
