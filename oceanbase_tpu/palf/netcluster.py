"""Networked PALF group: one local replica per process, peers over RPC.

Reference analog: PalfHandleImpl's network path — submit_log on the
leader, receive_log on followers (src/logservice/palf/
palf_handle_impl.cpp:406, :3235), election RPCs (palf/election/), and
the log fetch/catch-up protocol.  The in-process `PalfCluster` keeps the
same protocol with direct calls; this class speaks it over
`oceanbase_tpu.net.rpc` so each replica lives in its own OS process.

Interface-compatible with `PalfCluster` where the tenant/tx layers touch
it: ``append(payloads) -> committed_lsn``, ``committed_lsn()``,
``elect()``, ``leader()``/``is_leader``, ``close()``.  A non-leader
``append`` raises ``NotLeader`` with the current leader hint so the node
layer can forward the write (≙ location-cache-driven retry on
OB_NOT_MASTER).

RPC endpoints this class registers on its node's server:
    palf.vote(term, candidate, last_lsn, last_term) -> reply dict
    palf.accept(prev_lsn, prev_term, entries, leader_id, commit) -> bool
    palf.commit(commit_lsn, leader_id)
    palf.state() -> {last_lsn, committed_lsn, term, role}
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from oceanbase_tpu.palf.cluster import NoQuorum, NotLeader
from oceanbase_tpu.palf.election import (
    ElectionAcceptor,
    ElectionProposer,
    VoteReply,
    VoteRequest,
)
from oceanbase_tpu.palf.log import LogEntry, PalfReplica


def _encode_entries(entries: list[LogEntry]) -> list[dict]:
    return [{"term": e.term, "lsn": e.lsn, "payload": e.payload}
            for e in entries]


def _decode_entries(raw: list[dict]) -> list[LogEntry]:
    return [LogEntry(int(d["term"]), int(d["lsn"]), bytes(d["payload"]))
            for d in raw]


class NetPalf:
    def __init__(self, node_id: int, peers: dict[int, "RpcClient"],
                 log_dir: str | None = None,
                 apply_cb: Optional[Callable] = None,
                 lease_ms: int = 2000, recovery=None):
        """peers: {node_id: RpcClient} for every OTHER node."""
        self.node_id = node_id
        self.peers = peers
        self.replica = PalfReplica(node_id, log_dir, apply_cb=apply_cb,
                                   recovery=recovery)
        self.acceptor = ElectionAcceptor(self.replica)
        self.proposer = ElectionProposer(self.replica, self._vote_rpc,
                                         lease_ms=lease_ms)
        self.leader_hint: int | None = None
        # LSNs this process originated as leader: their effects already
        # exist in the local engine via the write path, so the apply
        # callback must skip them (followers apply; ≙ applyservice
        # firing commit callbacks on the leader vs replayservice replay)
        self.local_lsns: set[int] = set()
        self._lock = threading.RLock()

    # ------------------------------------------------------------------
    # outgoing RPC
    # ------------------------------------------------------------------
    def _vote_rpc(self, peer_id: int, req: VoteRequest):
        cli = self.peers.get(peer_id)
        if cli is None:
            return None
        try:
            r = cli.call("palf.vote", term=req.term,
                         candidate=req.candidate, last_lsn=req.last_lsn,
                         last_term=req.last_term)
        except OSError:
            return None
        return VoteReply(int(r["term"]), bool(r["granted"]),
                         int(r["voter"]))

    def _ship_to(self, peer_id: int, commit: int) -> bool:
        """Push the suffix a follower is missing (walk back on term
        mismatch — ≙ fetch-log catch-up)."""
        cli = self.peers.get(peer_id)
        if cli is None:
            return False
        r = self.replica
        try:
            st = cli.call("palf.state")
            if int(st.get("term", 0)) > r.current_term:
                # the cluster moved on to a newer term: we are a stale
                # leader — stop shipping (our lease lapses, we step down)
                return False
            prev = min(r.last_lsn(), int(st["last_lsn"]))
            while prev > 0:
                batch = r.entries_from(prev)
                if batch is None:
                    # prev predates our WAL-recycle base: the history
                    # is gone — this follower needs the rebuild plane
                    return False
                ok = cli.call(
                    "palf.accept", prev_lsn=prev,
                    prev_term=r.term_at(prev),
                    entries=_encode_entries(batch),
                    leader_id=self.node_id, commit=commit,
                    term=r.current_term)
                if ok:
                    return True
                prev -= 1
            batch = r.entries_from(0)
            if batch is None:
                return False  # recycled: cannot ship from lsn 0
            return bool(cli.call(
                "palf.accept", prev_lsn=0, prev_term=0,
                entries=_encode_entries(batch),
                leader_id=self.node_id, commit=commit,
                term=r.current_term))
        except OSError:
            return False

    # ------------------------------------------------------------------
    # incoming RPC handlers (registered by the node server)
    # ------------------------------------------------------------------
    def handlers(self) -> dict:
        return {
            "palf.vote": self._on_vote,
            "palf.accept": self._on_accept,
            "palf.commit": self._on_commit,
            "palf.state": self._on_state,
        }

    def _on_vote(self, term, candidate, last_lsn, last_term):
        rep = self.acceptor.on_vote_request(
            VoteRequest(int(term), int(candidate), int(last_lsn),
                        int(last_term)))
        return {"term": rep.term, "granted": rep.granted,
                "voter": rep.voter}

    def _on_accept(self, prev_lsn, prev_term, entries, leader_id,
                   commit, term=None):
        with self._lock:
            r = self.replica
            es = _decode_entries(entries)
            # sender's leadership term; older wires omit it — fall back
            # to the shipped entries' last term as before
            sender_term = (int(term) if term is not None
                           else (es[-1].term if es else None))
            if sender_term is not None and sender_term < r.current_term:
                # Raft safety: a DEPOSED leader's append must not
                # truncate the new leader's entries (its conflicting
                # suffix would overwrite possibly-committed log) — and
                # must not count as an ack that refreshes its lease
                return False
            # a valid append refreshes follower state: the sender holds
            # a majority-granted lease for its term
            if sender_term is not None and sender_term >= r.current_term:
                r.current_term = sender_term
                if r.role == "leader" and leader_id != self.node_id:
                    r.role = "follower"
                self.leader_hint = int(leader_id)
            ok = r.accept(int(prev_lsn), int(prev_term), es)
            if ok:
                self.leader_hint = int(leader_id)
        if ok:
            # apply OUTSIDE self._lock: the apply callback reaches into
            # tx/engine state whose write paths call back into this
            # class (commit -> append -> self._lock) from other threads —
            # holding the palf lock across it would order the two locks
            # both ways and deadlock under leadership churn
            r.advance_commit(min(int(commit), r.last_lsn()))
        return ok

    def _on_commit(self, commit_lsn, leader_id, term=None):
        with self._lock:
            if term is not None and int(term) < self.replica.current_term:
                return False  # stale leader's commit point: ignore
            self.leader_hint = int(leader_id)
        # apply outside self._lock (same rationale as _on_accept)
        self.replica.advance_commit(
            min(int(commit_lsn), self.replica.last_lsn()))
        return True

    def _on_state(self):
        r = self.replica
        return {"last_lsn": r.last_lsn(),
                "committed_lsn": r.committed_lsn,
                "term": r.current_term, "role": r.role,
                "leader_hint": self.leader_hint}

    # ------------------------------------------------------------------
    # leadership
    # ------------------------------------------------------------------
    @property
    def is_leader(self) -> bool:
        return (self.replica.role == "leader"
                and self.proposer.lease_valid())

    def elect(self) -> int:
        """Campaign for leadership of this group."""
        with self._lock:
            if self.proposer.campaign(sorted(self.peers)):
                self.leader_hint = self.node_id
                # Raft safety: commit prior-term entries via a no-op in
                # the new term
                self._replicate([b'{"op": "noop"}'])
                won = True
            else:
                won = False
        if won:
            # catch-up residue from follower days applies OUTSIDE the
            # palf lock (see _on_accept)
            self.replica.drain_applies()
            return self.node_id
        raise NoQuorum(f"node {self.node_id} lost the election")

    def on_peer_down(self, peer_id: int, attempts: int = 8) -> bool:
        """Failure-detector hook: the cluster health monitor declared
        ``peer_id`` down.  If that peer is the replica we believe leads,
        campaign IMMEDIATELY instead of waiting for the next write to
        pay out the remaining lease (≙ takeover election on a dead
        leader's lease, palf/election).  The survivors of a 3-node
        cluster detect the death near-simultaneously and would split the
        vote forever if symmetric, so campaigns are staggered by a
        node-id offset plus randomized, growing backoff (≙ election
        priority + randomized timeouts).  -> True if this node won."""
        if self.replica.role == "leader":
            return False
        if self.leader_hint is not None and self.leader_hint != peer_id:
            return False  # somebody else leads as far as we know
        stagger = 0.12 * ((self.node_id * 7) % 5)
        for attempt in range(max(attempts, 1)):
            time.sleep(stagger
                       + random.uniform(0.02, 0.15) * (attempt + 1))
            if self.replica.role == "leader":
                return True
            hint = self.leader_hint
            if hint is not None and hint not in (peer_id, self.node_id):
                return False  # a rival already won; follow it
            try:
                self.elect()
                return True
            except NoQuorum:
                continue
            except OSError:
                continue
        return False

    def ensure_leader(self, campaign: bool = False):
        if self.is_leader:
            return
        if campaign:
            self.elect()
            return
        raise NotLeader(f"node {self.node_id} is not the leader "
                        f"(hint: {self.leader_hint})")

    # ------------------------------------------------------------------
    # append path (PalfCluster-compatible surface)
    # ------------------------------------------------------------------
    def append(self, payloads: list[bytes]) -> int:
        with self._lock:
            self.ensure_leader()
            out = self._replicate(payloads)
        # deferred applies (drain=False in _replicate) run lock-free
        self.replica.drain_applies()
        return out

    def _replicate(self, payloads: list[bytes]) -> int:
        r = self.replica
        entries = r.leader_append(payloads)
        commit_target = entries[-1].lsn if entries else r.last_lsn()
        acks = 1
        for pid in sorted(self.peers):
            if self._ship_to(pid, r.committed_lsn):
                acks += 1
        quorum = (len(self.peers) + 1) // 2 + 1
        if acks < quorum:
            raise NoQuorum(
                f"append replicated to {acks}/{len(self.peers) + 1}")
        # mark leader-originated lsns only AFTER quorum: committed
        # entries are never replaced (Raft), so the skip in
        # _apply_entry is safe — whereas marking a NoQuorum'd batch
        # would make this node skip-apply whatever a later leader
        # commits at those lsns (its replacement entries, or even our
        # own, whose effects the failed write path never applied)
        self.local_lsns.update(e.lsn for e in entries)
        # caller holds self._lock: defer apply callbacks to the
        # drain_applies() after the lock releases (append/elect)
        r.advance_commit(commit_target, drain=False)
        self.proposer.refresh_lease()
        for pid, cli in self.peers.items():
            try:
                cli.call("palf.commit", commit_lsn=r.committed_lsn,
                         leader_id=self.node_id, term=r.current_term)
            except OSError:
                pass
        return r.committed_lsn

    def tick(self):
        """Leader heartbeat: catch followers up + refresh lease."""
        with self._lock:
            if self.replica.role != "leader":
                return
            acks = 1
            for pid in sorted(self.peers):
                if self._ship_to(pid, self.replica.committed_lsn):
                    acks += 1
            if acks >= (len(self.peers) + 1) // 2 + 1:
                self.proposer.refresh_lease()

    # ------------------------------------------------------------------
    def committed_lsn(self) -> int:
        return self.replica.committed_lsn

    def recycle(self, upto_lsn: int) -> int:
        """WAL recycle of THIS process's replica (peers recycle on
        their own checkpoint cadence); -> bytes reclaimed on disk."""
        return self.replica.recycle(upto_lsn)

    def close(self):
        self.replica.close()
