"""Replicated log replica: terms, append, group commit, persistence.

Reference analog: PalfHandleImpl + LogSlidingWindow + LogEngine/LogIOWorker
(src/logservice/palf/palf_handle_impl.cpp:406 submit_log, :3235
receive_log; log_sliding_window.cpp group buffers; log_engine.cpp disk IO).

Model (single log stream): entries are (term, lsn, payload bytes).  The
leader assigns LSNs, appends to its local log, and ships entries to
followers; an entry is committed once a majority has persisted it, after
which the apply callback fires in LSN order on every replica (leader
apply ≙ applyservice, follower ≙ replayservice).  Consistency follows the
standard term-match rule: a follower accepts entries only when the
previous entry's term matches (truncating divergent suffixes).
"""

from __future__ import annotations

import errno
import json
import logging
import os
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from oceanbase_tpu.native import crc64
from oceanbase_tpu.server import metrics as qmetrics

log = logging.getLogger(__name__)

# replication-plane accounting (host side; server/metrics.py registry)
qmetrics.declare("palf.appends", "counter",
                 "leader group-append batches")
qmetrics.declare("palf.entries_appended", "counter",
                 "log entries appended on the leader")
qmetrics.declare("palf.fsyncs", "counter",
                 "durable log fsyncs (append path)")
qmetrics.declare("palf.fsync_s", "histogram",
                 "append-path fsync latency", unit="s")
qmetrics.declare("palf.entries_applied", "counter",
                 "committed entries applied through the state machine")

_HDR = struct.Struct("<QQIQ")  # term, lsn(index), payload_len, crc64
_MAGIC = b"OBTPULG1"  # file magic + format version (bump on layout change)

# WAL-recycle base record: a recycled log file starts with one entry
# carrying this payload whose (term, lsn) name the last RECYCLED entry
# — everything at/below it was applied AND captured by a checkpoint, so
# recovery resumes from the manifest + the suffix (≙ palf base lsn /
# rebuild point advanced by the checkpoint service).  It rides the
# ordinary entry format, so scan_wal/crc verification cover it.
_BASE_PAYLOAD = b"\x00PALF_BASE\x00"

# quarantine retention (shared with the data-dir boundary):
# storage/integrity.py owns the pruner, re-exported here for callers
from oceanbase_tpu.storage.integrity import (  # noqa: E402
    QUARANTINE_KEEP,
    QUARANTINE_MAX_AGE_S,
    prune_quarantine,
)


def scan_wal(buf: bytes) -> tuple[list[LogEntry], int, int]:
    """Shared WAL tail scan over a log file body (after the magic):
    -> (entries, valid_off, crc_failed_lsn).  ``valid_off`` is the end
    of the last fully-validated entry; ``crc_failed_lsn`` is non-zero
    when the scan stopped at a COMPLETE entry failing its crc64 (rot)
    rather than an incomplete torn append.  Every consumer of the
    on-disk entry format goes through here — recovery, backup
    verification, PITR — so a layout bump changes one scanner."""
    entries: list[LogEntry] = []
    off = len(_MAGIC)
    valid_off = off
    crc_failed_lsn = 0
    while off + _HDR.size <= len(buf):
        term, lsn, plen, crc = _HDR.unpack_from(buf, off)
        off += _HDR.size
        if off + plen > len(buf):
            break  # torn tail write: discard (≙ log tail scan)
        payload = buf[off:off + plen]
        if crc64(struct.pack("<QQ", term, lsn) + payload) != crc:
            crc_failed_lsn = lsn
            break
        entries.append(LogEntry(term, lsn, payload))
        off += plen
        valid_off = off
    return entries, valid_off, crc_failed_lsn


@dataclass
class LogEntry:
    term: int
    lsn: int          # 1-based dense index
    payload: bytes

    def encode(self) -> bytes:
        """Wire/disk format with a crc64 integrity checksum over
        (term, lsn, payload) — ≙ the reference's log-entry checksums
        (accumulated data checksums in the log group entries)."""
        crc = crc64(struct.pack("<QQ", self.term, self.lsn) + self.payload)
        return _HDR.pack(self.term, self.lsn, len(self.payload), crc) + \
            self.payload


class PalfReplica:
    """One replica of one log stream (host state machine + disk log)."""

    def __init__(self, replica_id: int, log_dir: str | None = None,
                 apply_cb: Optional[Callable] = None, recovery=None):
        self.replica_id = replica_id
        self.log_dir = log_dir
        self.apply_cb = apply_cb
        # recovery-event sink (storage/recovery.py RecoveryState or
        # None): quarantined/truncated WAL bytes surface in gv$recovery
        self.recovery = recovery
        # disk-fault plane hook (net/faults.py), armed by NodeServer
        self.faults = None
        # WAL recycle point: entries at/below base_lsn were dropped
        # from memory AND disk (their effects live in the engine
        # checkpoint); entries[i].lsn == base_lsn + i + 1
        self.base_lsn = 0
        self.base_term = 0
        self.entries: list[LogEntry] = []   # suffix, lsn = base+idx+1
        self.committed_lsn = 0
        self.applied_lsn = 0
        self.current_term = 0
        self.voted_for: dict[int, int] = {}  # term -> candidate
        self.role = "follower"
        self._lock = threading.RLock()
        # serializes apply callbacks WITHOUT holding self._lock: the
        # callback reaches into engine/tx state whose own paths call
        # back into the log (commit -> append), so running it under a
        # log lock would order locks both ways (deadlock under churn)
        self._apply_mutex = threading.Lock()
        self._log_f = None
        if log_dir is not None:
            os.makedirs(log_dir, exist_ok=True)
            self._recover()

    # ------------------------------------------------------------------
    # persistence (≙ LogEngine block files; single append file here)
    # ------------------------------------------------------------------
    def _log_path(self):
        return os.path.join(self.log_dir, f"replica_{self.replica_id}.log")

    def _persist(self, entries: list[LogEntry]):
        """Durably append ``entries``.  A write failure — real ENOSPC/
        EIO or an armed errno fault — UNWINDS: the file is truncated
        back to the pre-write offset (no half entry left behind), the
        desynced buffered handle is dropped, and the failure surfaces
        as typed DiskFull/DiskIOError, never a bare OSError."""
        if self.log_dir is None:
            return
        path = self._log_path()
        buf = b"".join(e.encode() for e in entries)
        pre_off = None
        try:
            if self._log_f is None:
                fresh = not os.path.exists(path) or \
                    os.path.getsize(path) == 0
                self._log_f = open(path, "ab")
                if fresh:
                    self._log_f.write(_MAGIC)
            # flush the header/prior bytes so tell() is the real
            # pre-write file offset the unwind truncates back to
            self._log_f.flush()
            pre_off = self._log_f.tell()
            if self.faults is not None and entries:
                # errno injection INSIDE the writer: enospc/eio raise
                # with nothing written; partial persists a seeded
                # fraction of the batch then fails — the torn-write
                # case the unwind below must clean up
                cut = self.faults.check_write("wal", path,
                                              nbytes=len(buf))
                if cut is not None:
                    self._log_f.write(buf[:cut])
                    self._log_f.flush()
                    raise OSError(errno.ENOSPC,
                                  "fault: partial WAL write", path)
            self._log_f.write(buf)
            t0 = time.perf_counter()
            self._log_f.flush()
            os.fsync(self._log_f.fileno())
        except OSError as exc:
            self._unwind_append(pre_off)
            from oceanbase_tpu.server.diskmgr import wrap_disk_error

            raise wrap_disk_error(
                exc, f"palf replica {self.replica_id} wal append"
            ) from exc
        qmetrics.inc("palf.fsyncs")
        qmetrics.observe("palf.fsync_s", time.perf_counter() - t0)
        if self.faults is not None:
            self.faults.act_disk("wal", path)

    def _unwind_append(self, pre_off: int | None):
        """Roll the append file back to the pre-write offset after a
        failed write: the buffered handle may hold half an entry (its
        view of the file offset desynced from disk), so it is dropped
        and the file physically truncated — the next append reopens
        clean, and a crash before this runs is covered by the recovery
        scan truncating the torn tail."""
        try:
            if self._log_f is not None:
                self._log_f.close()
        except OSError:
            pass  # close may flush the poisoned buffer and fail again
        self._log_f = None
        if pre_off is None:
            return
        try:
            with open(self._log_path(), "r+b") as f:
                f.truncate(pre_off)
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            # best effort: recovery's tail scan handles what remains
            log.warning("palf replica %d: could not truncate back to "
                        "%d after failed append", self.replica_id,
                        pre_off)

    def _truncate_disk(self):
        """Rewrite the on-disk log after a suffix truncation (or a
        prefix recycle): tmp + fsync + atomic replace, with a base
        record leading a recycled file.  A failed rewrite leaves the
        OLD file intact; the caller resyncs memory from it."""
        if self.log_dir is None:
            return
        if self._log_f:
            self._log_f.close()
            self._log_f = None
        path = self._log_path()
        tmp = path + ".tmp"
        try:
            if self.faults is not None:
                self.faults.check_write("wal", path)
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                if self.base_lsn > 0:
                    f.write(LogEntry(self.base_term, self.base_lsn,
                                     _BASE_PAYLOAD).encode())
                for e in self.entries:
                    f.write(e.encode())
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as exc:
            try:
                os.remove(tmp)
            except OSError:
                pass
            from oceanbase_tpu.server.diskmgr import wrap_disk_error

            raise wrap_disk_error(
                exc, f"palf replica {self.replica_id} wal rewrite"
            ) from exc

    def _resync_from_disk(self):
        """Reload in-memory entries from the on-disk log (the recovery
        scan, minus quarantine) — used when a disk rewrite failed and
        the old file is authoritative again."""
        self._log_f = None
        self.entries = []
        self.base_lsn = self.base_term = 0
        path = self._log_path()
        if self.log_dir is None or not os.path.exists(path):
            return
        with open(path, "rb") as f:
            buf = f.read()
        if not buf.startswith(_MAGIC):
            return
        entries, _valid_off, _crc_fail = scan_wal(buf)
        if entries and entries[0].payload == _BASE_PAYLOAD:
            self.base_lsn = entries[0].lsn
            self.base_term = entries[0].term
            entries = entries[1:]
        self.entries = entries
        self.committed_lsn = min(self.committed_lsn, self.last_lsn())
        self.applied_lsn = min(self.applied_lsn, self.last_lsn())

    def recycle(self, upto_lsn: int) -> int:
        """Physically reclaim log-disk space: drop entries at/below
        ``upto_lsn`` from memory and disk (clamped to the commit AND
        apply points — never an entry whose effects are not already in
        the engine; the caller additionally clamps to the persisted
        checkpoint replay point).  -> bytes reclaimed on disk."""
        with self._lock:
            upto = min(int(upto_lsn), self.committed_lsn,
                       self.applied_lsn)
            if upto <= self.base_lsn:
                return 0
            drop = upto - self.base_lsn
            self.base_term = self.entries[drop - 1].term
            del self.entries[:drop]
            self.base_lsn = upto
            if self.log_dir is None:
                return 0
            path = self._log_path()
            try:
                before = os.path.getsize(path)
            except OSError:
                before = 0
            try:
                self._truncate_disk()
            except Exception:
                # rewrite failed: the OLD file (full history) is still
                # authoritative — restore memory to match it
                self._resync_from_disk()
                raise
            try:
                after = os.path.getsize(path)
            except OSError:
                after = 0
            return max(0, before - after)

    def _recover(self):
        path = self._log_path()
        if not os.path.exists(path):
            return
        with open(path, "rb") as f:
            buf = f.read()
        if not buf.startswith(_MAGIC):
            # unknown/older format: refuse to guess — quarantine the file
            # so a later append cannot land BEHIND unreadable bytes that
            # the next recovery would stop at (peer catch-up restores
            # state; a format migration tool would go here).  Quarantine
            # files get unique names, surface in gv$recovery
            # (phase=quarantine) and are retention-capped by count/age —
            # repeated corruption must never grow the dir unbounded or
            # vanish without an operator-visible trace.
            if buf:
                qpath = f"{path}.corrupt.{time.time_ns():x}"
                os.replace(path, qpath)
                log.warning("palf replica %d: quarantined %d unreadable "
                            "log bytes to %s", self.replica_id, len(buf),
                            qpath)
                if self.recovery is not None:
                    self.recovery.record(
                        "quarantine", nbytes=len(buf),
                        note=f"wal bad magic -> {os.path.basename(qpath)}")
                prune_quarantine(self.log_dir)
            return
        # crc_failed_lsn != 0: the scan stopped at a COMPLETE entry
        # failing its crc (rot — worth a gv$recovery quarantine row
        # below), vs 0 for an ordinary torn append
        self.entries, valid_off, crc_failed_lsn = scan_wal(buf)
        if self.entries and self.entries[0].payload == _BASE_PAYLOAD:
            # recycled log: the base record names the last dropped
            # entry — everything at/below it is applied AND in the
            # engine checkpoint, so the commit/apply points resume
            # there and the suffix replays on top
            base = self.entries[0]
            self.base_lsn = base.lsn
            self.base_term = base.term
            self.entries = self.entries[1:]
            self.committed_lsn = self.base_lsn
            self.applied_lsn = self.base_lsn
            self.current_term = self.base_term
        if valid_off < len(buf):
            # torn/corrupt tail bytes follow the last valid entry.  They
            # MUST be physically truncated before any append: _persist
            # reopens in append mode, and entries written after garbage
            # are unreachable to the next recovery (it stops scanning at
            # the garbage) — silently losing them.
            with open(path, "r+b") as f:
                f.truncate(valid_off)
                f.flush()
                os.fsync(f.fileno())
            log.warning(
                "palf replica %d: truncated %d torn/corrupt tail bytes "
                "(log keeps %d entries)", self.replica_id,
                len(buf) - valid_off, len(self.entries))
            if crc_failed_lsn and self.recovery is not None:
                # rot (vs an ordinary crash's torn append, which is
                # expected and stays a log line): surface it
                self.recovery.record(
                    "quarantine", nbytes=len(buf) - valid_off,
                    wal_start_lsn=crc_failed_lsn,
                    note=f"wal entry lsn={crc_failed_lsn} crc mismatch;"
                         " tail truncated (catch-up re-ships)")
        if self.entries:
            self.current_term = self.entries[-1].term

    # ------------------------------------------------------------------
    # leader path
    # ------------------------------------------------------------------
    def leader_append(self, payloads: list[bytes]) -> list[LogEntry]:
        """Group append (≙ submit_log into the sliding window's group
        buffer): assigns LSNs and persists locally in one fsync."""
        with self._lock:
            assert self.role == "leader"
            out = []
            for p in payloads:
                e = LogEntry(self.current_term, self.last_lsn() + 1, p)
                self.entries.append(e)
                out.append(e)
            try:
                self._persist(out)
            except Exception:
                # memory must not run ahead of a failed durable append:
                # a later append after the truncate-back would leave an
                # LSN gap on disk that recovery cannot scan across
                del self.entries[len(self.entries) - len(out):]
                raise
            qmetrics.inc("palf.appends")
            qmetrics.inc("palf.entries_appended", len(out))
            return out

    def last_lsn(self) -> int:
        with self._lock:
            return self.base_lsn + len(self.entries)

    def term_at(self, lsn: int) -> int:
        with self._lock:
            if lsn == 0:
                return 0
            if lsn == self.base_lsn:
                return self.base_term
            if lsn < self.base_lsn:
                return -1  # recycled away: unservable history
            if lsn <= self.base_lsn + len(self.entries):
                return self.entries[lsn - 1 - self.base_lsn].term
            return -1

    def entries_from(self, lsn: int) -> list[LogEntry] | None:
        """Entries with lsn > ``lsn`` (the catch-up batch after a
        matching prefix at ``lsn``); None when ``lsn`` predates the
        recycle point — that follower needs the rebuild plane, the
        recycled history cannot be served."""
        with self._lock:
            if lsn < self.base_lsn:
                return None
            return list(self.entries[lsn - self.base_lsn:])

    def entries_between(self, start_lsn: int, end_lsn: int
                        ) -> list[LogEntry]:
        """Entries with start < lsn <= end (the boot-replay slice).
        Entries recycled below base_lsn are by construction at/below
        the persisted checkpoint replay point, so a start clamped to
        that point never reaches them."""
        with self._lock:
            lo = max(0, start_lsn - self.base_lsn)
            hi = max(0, end_lsn - self.base_lsn)
            return list(self.entries[lo:hi])

    # ------------------------------------------------------------------
    # follower path (≙ receive_log)
    # ------------------------------------------------------------------
    def accept(self, prev_lsn: int, prev_term: int,
               entries: list[LogEntry]) -> bool:
        with self._lock:
            base = self.base_lsn
            if prev_lsn > self.last_lsn():
                return False  # gap
            if prev_lsn < base:
                return False  # prefix recycled: cannot verify the match
            if prev_lsn > base and \
                    self.entries[prev_lsn - 1 - base].term != prev_term:
                return False  # divergent history at prev
            truncated = False
            appended: list[LogEntry] = []
            for e in entries:
                if e.lsn <= base:
                    continue  # at/below the recycle point: applied long ago
                if e.lsn <= self.last_lsn():
                    if self.entries[e.lsn - 1 - base].term != e.term:
                        del self.entries[e.lsn - 1 - base:]
                        truncated = True
                    else:
                        continue  # duplicate
                if e.lsn != self.last_lsn() + 1:
                    return False  # non-contiguous batch: reject
                self.entries.append(e)
                appended.append(e)
            try:
                if truncated:
                    self._truncate_disk()  # rewrite incl. appended suffix
                else:
                    self._persist(appended)
            except Exception:
                if truncated:
                    # the OLD file survived the failed rewrite: make
                    # memory match it again (as if this accept never ran)
                    self._resync_from_disk()
                else:
                    del self.entries[len(self.entries) - len(appended):]
                raise
            return True

    # ------------------------------------------------------------------
    # commit + apply (≙ committed_end_lsn advance + apply/replay service)
    # ------------------------------------------------------------------
    def advance_commit(self, commit_lsn: int, drain: bool = True):
        """Advance the commit point; ``drain=False`` defers the apply
        callbacks to an explicit ``drain_applies()`` — for callers that
        hold locks the callback's downstream paths also take."""
        with self._lock:
            commit_lsn = min(commit_lsn, self.base_lsn + len(self.entries))
            if commit_lsn > self.committed_lsn:
                self.committed_lsn = commit_lsn
        if drain:
            self._apply_committed()

    def drain_applies(self):
        self._apply_committed()

    def _apply_committed(self):
        """Drain committed-but-unapplied entries through the callback in
        LSN order.  The apply mutex keeps the drain serial and ordered
        across concurrent advance_commit callers; the replica lock is
        NOT held across a callback (see _apply_mutex), and applied_lsn
        only advances AFTER the callback returns, so consumers gating on
        it (e.g. the DTL snapshot check) never run ahead of the engine.
        A non-blocking acquire avoids deadlock when the current drainer's
        callback is itself waiting on a lock this caller holds: the
        active drainer re-reads the commit point each iteration, and any
        entries it misses at the exit race drain at the next trigger."""
        if not self._apply_mutex.acquire(blocking=False):
            return  # an active drainer will observe the new commit point
        try:
            while True:
                with self._lock:
                    if self.applied_lsn >= self.committed_lsn:
                        return
                    # applied_lsn never trails base_lsn: recycle clamps
                    # to the apply point, and recovery of a recycled
                    # log resumes both points at the base
                    e = self.entries[self.applied_lsn - self.base_lsn]
                if self.apply_cb is not None:
                    self.apply_cb(e)
                qmetrics.inc("palf.entries_applied")
                with self._lock:
                    self.applied_lsn += 1
        finally:
            self._apply_mutex.release()

    def close(self):
        if self._log_f:
            self._log_f.close()
            self._log_f = None
