"""Lease-based leader election.

Reference analog: src/logservice/palf/election — ElectionImpl
(algorithm/election_impl.h:43), proposer/acceptor split
(election_proposer.cpp / election_acceptor.cpp), with leader leases and
priority comparison.

Model: candidates request votes for a term; an acceptor grants at most one
vote per term (persisted via the replica's voted_for) and only to
candidates whose log is at least as up-to-date (last term, last lsn).  A
leader holds a lease it must refresh by heartbeating a majority; an
expired lease triggers a new election with randomized timeouts
(priority = longer log wins, then lower id, ≙ election priority)."""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from oceanbase_tpu.server import metrics as qmetrics

qmetrics.declare("palf.elections", "counter",
                 "election campaigns started on this node")
qmetrics.declare("palf.elections_won", "counter",
                 "campaigns that reached quorum")


@dataclass
class VoteRequest:
    term: int
    candidate: int
    last_lsn: int
    last_term: int


@dataclass
class VoteReply:
    term: int
    granted: bool
    voter: int


class ElectionAcceptor:
    """Vote-granting side, one per replica."""

    def __init__(self, replica):
        self.replica = replica
        self._lock = threading.Lock()

    def on_vote_request(self, req: VoteRequest) -> VoteReply:
        r = self.replica
        with self._lock:
            if req.term < r.current_term:
                return VoteReply(r.current_term, False, r.replica_id)
            if req.term > r.current_term:
                r.current_term = req.term
                r.role = "follower"
            already = r.voted_for.get(req.term)
            if already is not None and already != req.candidate:
                return VoteReply(r.current_term, False, r.replica_id)
            # up-to-date check (no committed-entry loss across leaders)
            my_last = r.last_lsn()
            my_last_term = r.term_at(my_last)
            ok = (req.last_term, req.last_lsn) >= (my_last_term, my_last)
            if ok:
                r.voted_for[req.term] = req.candidate
            return VoteReply(r.current_term, ok, r.replica_id)


class ElectionProposer:
    """Candidate side: runs one election round for its replica."""

    def __init__(self, replica, peers_rpc, lease_ms: int = 400):
        self.replica = replica
        self.peers_rpc = peers_rpc  # callable: (peer_id, VoteRequest) -> VoteReply | None
        self.lease_ms = lease_ms
        self.lease_expire = 0.0

    def randomized_timeout(self) -> float:
        return (self.lease_ms + random.randint(0, self.lease_ms)) / 1000.0

    def campaign(self, peer_ids) -> bool:
        qmetrics.inc("palf.elections")
        r = self.replica
        r.current_term += 1
        term = r.current_term
        r.voted_for[term] = r.replica_id
        r.role = "candidate"
        votes = 1
        req = VoteRequest(term, r.replica_id, r.last_lsn(),
                          r.term_at(r.last_lsn()))
        for pid in peer_ids:
            reply = self.peers_rpc(pid, req)
            if reply is None:
                continue
            if reply.term > r.current_term:
                r.current_term = reply.term
                r.role = "follower"
                return False
            if reply.granted:
                votes += 1
        quorum = (len(peer_ids) + 1) // 2 + 1
        if votes >= quorum and r.current_term == term:
            r.role = "leader"
            self.refresh_lease()
            qmetrics.inc("palf.elections_won")
            return True
        r.role = "follower"
        return False

    def refresh_lease(self):
        self.lease_expire = time.monotonic() + self.lease_ms / 1000.0

    def lease_valid(self) -> bool:
        return time.monotonic() < self.lease_expire
