"""PALF — a Paxos-family replicated log (host control plane).

Reference analog: src/logservice/palf (57k LoC): PalfHandleImpl
(submit_log palf_handle_impl.cpp:406, receive_log :3235), the sliding
window group-buffering (log_sliding_window.cpp), lease-based election
(election/algorithm/election_impl.h:43) and follower replay
(replayservice).

The TPU build keeps replication on the host by design (SURVEY north star).
This package implements a leader-based majority-ack replicated log with:
- terms + lease election with randomized timeouts (election.py)
- group commit: appends batch into group buffers before fsync (log.py)
- an in-process multi-replica cluster harness over queues — the analog of
  mittest/palf_cluster (SURVEY §4 tier 3) — plus on-disk log files with
  crash recovery.
"""

from oceanbase_tpu.palf.log import LogEntry, PalfReplica
from oceanbase_tpu.palf.cluster import PalfCluster

__all__ = ["LogEntry", "PalfReplica", "PalfCluster"]
