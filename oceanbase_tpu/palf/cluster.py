"""In-process PALF cluster: N replicas, message passing, failure injection.

Reference analog: the palf_cluster mittest harness
(mittest/palf_cluster/README.md) plus the runtime glue PalfEnv provides —
here the "RPC" is direct method calls guarded by a partition/down matrix
so tests can kill leaders and heal partitions (≙ errsim-driven failover
tests, SURVEY §4/§5.3).

Synchronous-replication model: ``append(payloads)`` on the leader ships to
every reachable follower and commits on majority persistence; commit
advances followers on the next append or an explicit ``tick()``
(heartbeat).  Election runs on demand via ``elect()`` or automatically
when an append finds no valid-lease leader.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from oceanbase_tpu.palf.election import (
    ElectionAcceptor,
    ElectionProposer,
    VoteRequest,
)
from oceanbase_tpu.palf.log import LogEntry, PalfReplica


class NotLeader(RuntimeError):
    pass


class NoQuorum(RuntimeError):
    pass


class PalfCluster:
    def __init__(self, n_replicas: int = 3, log_root: str | None = None,
                 apply_cb_factory: Optional[Callable] = None):
        self.replicas: dict[int, PalfReplica] = {}
        self.acceptors: dict[int, ElectionAcceptor] = {}
        self.proposers: dict[int, ElectionProposer] = {}
        self.down: set[int] = set()
        self._lock = threading.RLock()
        for i in range(1, n_replicas + 1):
            import os

            ldir = None if log_root is None else log_root
            cb = apply_cb_factory(i) if apply_cb_factory else None
            r = PalfReplica(i, ldir, apply_cb=cb)
            self.replicas[i] = r
            self.acceptors[i] = ElectionAcceptor(r)
            self.proposers[i] = ElectionProposer(r, self._vote_rpc)
        self.leader_id: int | None = None

    # ------------------------------------------------------------------
    # "network"
    # ------------------------------------------------------------------
    def _reachable(self, a: int, b: int) -> bool:
        return a not in self.down and b not in self.down

    def _vote_rpc(self, peer_id: int, req: VoteRequest):
        if not self._reachable(req.candidate, peer_id):
            return None
        return self.acceptors[peer_id].on_vote_request(req)

    # ------------------------------------------------------------------
    # election
    # ------------------------------------------------------------------
    def elect(self, candidate: int | None = None) -> int:
        """Run an election; returns the new leader id.
        ≙ election_proposer prepare/accept rounds."""
        with self._lock:
            alive = [i for i in self.replicas if i not in self.down]
            if not alive:
                raise NoQuorum("all replicas down")
            # candidate priority: longest log, then lowest id
            cands = [candidate] if candidate else sorted(
                alive, key=lambda i: (-self.replicas[i].last_lsn(), i))
            for cand in cands + alive:
                if cand in self.down:
                    continue
                peers = [i for i in self.replicas if i != cand]
                if self.proposers[cand].campaign(peers):
                    self.leader_id = cand
                    # demote others
                    for i, r in self.replicas.items():
                        if i != cand and r.role == "leader":
                            r.role = "follower"
                    self._reconcile_followers()
                    # Raft safety: prior-term entries commit only via a
                    # current-term entry — append a no-op (≙ reconfirm)
                    self._append_noop()
                    return cand
            raise NoQuorum("no candidate won")

    def _reconcile_followers(self):
        ldr = self.replicas[self.leader_id]
        for i, r in self.replicas.items():
            if i != ldr.replica_id and self._reachable(ldr.replica_id, i):
                self._ship(ldr, r)

    def _append_noop(self):
        ldr = self.replicas[self.leader_id]
        entries = ldr.leader_append([b'{"op": "noop"}'])
        acks = 1
        for i, r in self.replicas.items():
            if i == ldr.replica_id or not self._reachable(ldr.replica_id, i):
                continue
            if self._ship(ldr, r):
                acks += 1
        if acks >= len(self.replicas) // 2 + 1:
            ldr.advance_commit(entries[-1].lsn)
            self._broadcast_commit(ldr.committed_lsn)

    def leader(self) -> PalfReplica:
        if self.leader_id is None or self.leader_id in self.down or \
                self.replicas[self.leader_id].role != "leader" or \
                not self.proposers[self.leader_id].lease_valid():
            self.elect()
        return self.replicas[self.leader_id]

    # ------------------------------------------------------------------
    # append path (≙ submit_log -> replicate -> majority ack -> commit)
    # ------------------------------------------------------------------
    def append(self, payloads: list[bytes]) -> int:
        """Group-append on the leader; returns committed end LSN."""
        from oceanbase_tpu.server.errsim import ERRSIM

        ERRSIM.hit("palf.append")
        with self._lock:
            ldr = self.leader()
            entries = ldr.leader_append(payloads)
            acks = 1
            for i, r in self.replicas.items():
                if i == ldr.replica_id:
                    continue
                if not self._reachable(ldr.replica_id, i):
                    continue
                if self._ship(ldr, r):
                    acks += 1
            quorum = len(self.replicas) // 2 + 1
            if acks < quorum:
                raise NoQuorum(
                    f"append replicated to {acks}/{len(self.replicas)}")
            # commit rule: majority-persisted entries of the current term
            commit = entries[-1].lsn if entries else ldr.last_lsn()
            ldr.advance_commit(commit)
            self.proposers[ldr.replica_id].refresh_lease()
            self._broadcast_commit(commit)
            return commit

    def _ship(self, ldr: PalfReplica, follower: PalfReplica) -> bool:
        """Bring a follower up to date from the leader's log
        (≙ fetch-log / push-log catch-up)."""
        # find the highest matching prefix, walking back on mismatch
        prev = min(ldr.last_lsn(), follower.last_lsn())
        while prev > 0 and follower.term_at(prev) != ldr.term_at(prev):
            prev -= 1
        batch = ldr.entries_from(prev)
        if batch is None:
            # the match point predates the leader's WAL-recycle base:
            # the history is physically gone — this follower needs the
            # rebuild plane, not catch-up
            return False
        return follower.accept(prev, ldr.term_at(prev), batch)

    def _broadcast_commit(self, commit_lsn: int):
        ldr_id = self.leader_id
        for i, r in self.replicas.items():
            if i == ldr_id or not self._reachable(ldr_id, i):
                continue
            r.advance_commit(min(commit_lsn, r.last_lsn()))

    def tick(self):
        """Heartbeat: refresh lease, catch followers up, advance commits."""
        with self._lock:
            if self.leader_id is None or self.leader_id in self.down:
                return
            ldr = self.replicas[self.leader_id]
            if ldr.role != "leader":
                return
            for i, r in self.replicas.items():
                if i != ldr.replica_id and self._reachable(ldr.replica_id, i):
                    self._ship(ldr, r)
            self.proposers[ldr.replica_id].refresh_lease()
            self._broadcast_commit(ldr.committed_lsn)

    # ------------------------------------------------------------------
    # failure injection (≙ errsim points)
    # ------------------------------------------------------------------
    def kill(self, replica_id: int):
        with self._lock:
            self.down.add(replica_id)
            if self.leader_id == replica_id:
                self.leader_id = None

    def revive(self, replica_id: int):
        with self._lock:
            self.down.discard(replica_id)

    def recycle(self, upto_lsn: int) -> int:
        """WAL recycle across every replica (each clamps to its own
        commit/apply point); -> bytes reclaimed on disk."""
        with self._lock:
            freed = 0
            for r in self.replicas.values():
                freed += r.recycle(upto_lsn)
            return freed

    def committed_lsn(self) -> int:
        if self.leader_id is not None and self.leader_id not in self.down:
            return self.replicas[self.leader_id].committed_lsn
        return max((r.committed_lsn for i, r in self.replicas.items()
                    if i not in self.down), default=0)

    def close(self):
        for r in self.replicas.values():
            r.close()
